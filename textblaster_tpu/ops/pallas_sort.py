"""VMEM-resident lexicographic sort — the pipeline's hottest device primitive.

Every duplicate-detection statistic (GopherRepetition line/paragraph/n-gram
dups, FineWeb duplicate lines — gopher_rep.rs:86-196, fineweb_quality.rs:
149-185 equivalents) reduces to "sort per-row (validity, hash, payload)
triples along the row".  XLA's ``lax.sort`` runs its compare-exchange network
with HBM round-trips between passes; this Pallas kernel keeps each block of
rows resident in VMEM for the entire bitonic network, so the ~log²(m)/2
stages cost lane-shuffles (``pltpu.roll``) and VPU selects instead of HBM
bandwidth.

The network is a standard bitonic sorter: static Python loops over
``(size, stride)`` stages — everything unrolls at trace time, all shapes
static, no gathers (partner access is a pair of circular lane shifts selected
by a constant parity mask), which keeps the kernel inside Mosaic's supported
op set.

Rows are independent; the grid tiles the batch dimension.  Row length must be
a power of two (all duplicate tables in :mod:`.stats` are sized to powers of
two by ``pipeline._table_sizes``).

Multi-device: Mosaic ``pallas_call`` custom calls carry no GSPMD partitioning
rule, so a program jitted with multi-device ``in_shardings`` cannot contain a
bare one.  ``sort2``/``sort3`` therefore take the target ``mesh`` explicitly
and wrap the kernel in ``shard_map`` over the data axis — each device sorts
its own row shard in VMEM; rows never cross devices, so no collective beyond
the resharding (if any) is inserted.  Off-TPU or for shapes the kernel cannot
tile, both fall back to ``lax.sort``, which partitions fine under GSPMD.

``TEXTBLAST_PALLAS_INTERPRET=1`` forces the Pallas *interpret* path on any
backend — used by the CPU-mesh tests to exercise the exact shard_map +
pallas_call program the TPU runs, minus the Mosaic lowering.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

try:  # moved out of experimental in newer JAX
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

try:  # pltpu is importable on all platforms; lowering is TPU-only.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

logger = logging.getLogger(__name__)

__all__ = [
    "sort2",
    "sort3",
    "pallas_sort2",
    "pallas_sort3",
    "pallas_sort_supported",
    # Shared Pallas helpers (used by ops.pallas_scan as well).
    "ROWS",
    "interpret_forced",
    "pallas_enabled",
    "roll_lanes",
    "shard_map",
]

_ROWS = 8  # sublane tile for int32
ROWS = _ROWS

#: Mesh axis the batch dimension is sharded over (parallel.mesh.DATA_AXIS;
#: duplicated here to keep this module importable standalone).
_DATA_AXIS = "data"


def pallas_enabled() -> bool:
    """Global Pallas escape hatch shared by every kernel (sort + scan):
    ``TEXTBLAST_PALLAS=off`` (or ``0``/``false``) and the older
    ``TEXTBLAST_NO_PALLAS=1`` both force the lax fallbacks everywhere.
    Re-read per call so tests can toggle it."""
    if os.environ.get("TEXTBLAST_PALLAS", "").lower() in ("off", "0", "false"):
        return False
    if os.environ.get("TEXTBLAST_NO_PALLAS"):
        return False
    return True


def interpret_forced() -> bool:
    return bool(os.environ.get("TEXTBLAST_PALLAS_INTERPRET"))


# Back-compat internal alias (older call sites / tests).
_interpret_forced = interpret_forced


def _lex_gt(a: Tuple[jax.Array, ...], b: Tuple[jax.Array, ...]) -> jax.Array:
    """Elementwise lexicographic ``a > b`` over equal-length key tuples."""
    gt = a[-1] > b[-1]
    for x, y in zip(reversed(a[:-1]), reversed(b[:-1])):
        gt = (x > y) | ((x == y) & gt)
    return gt


def roll_lanes(k: jax.Array, shift: int) -> jax.Array:
    """Circular right-roll along the lane axis.  ``pltpu.roll`` requires
    non-negative shifts; callers spell a left-roll by ``s`` as a right-roll
    by ``lanes - s``.  Works under interpret mode too (generic lowering ==
    ``jnp.roll``), so CPU tests run the exact kernel program the TPU lowers."""
    if pltpu is not None:
        return pltpu.roll(k, shift=shift, axis=1)
    return jnp.roll(k, shift, axis=1)  # pragma: no cover - pltpu unavailable


_roll = roll_lanes


def _bitonic_kernel(*refs):
    n = len(refs) // 2
    in_refs, out_refs = refs[:n], refs[n:]
    m = in_refs[0].shape[-1]
    ks = tuple(r[:] for r in in_refs)

    # In-kernel lane index (Pallas kernels cannot capture host constants).
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    size = 2
    while size <= m:
        stride = size // 2
        while stride >= 1:
            # Per-lane masks for this stage (stage parameters are static).
            is_lower = (lane & stride) == 0  # partner is at i+stride
            asc = (lane & size) == 0

            # pltpu.roll requires non-negative shifts; left-roll by `stride`
            # is a right-roll by `m - stride`.
            partners = tuple(
                jnp.where(is_lower, _roll(k, m - stride), _roll(k, stride))
                for k in ks
            )
            lower = tuple(jnp.where(is_lower, k, p) for k, p in zip(ks, partners))
            upper = tuple(jnp.where(is_lower, p, k) for k, p in zip(ks, partners))
            # Select between the two bool comparisons with i1 bitwise logic:
            # Mosaic cannot lower `select_n` with bool *operands* at >1 lane
            # tile (arith.trunci vector<i8> -> vector<i1> is unsupported).
            swap = (asc & _lex_gt(lower, upper)) | (
                jnp.logical_not(asc) & _lex_gt(upper, lower)
            )
            ks = tuple(jnp.where(swap, p, k) for k, p in zip(ks, partners))
            stride //= 2
        size *= 2

    for o, k in zip(out_refs, ks):
        o[:] = k


def _pallas_sort_n(ks: Tuple[jax.Array, ...], interpret: bool = False):
    """Row-wise ascending lexicographic sort of int32 ``[B, m]`` key arrays
    (``m`` a power of two, ``B`` a multiple of 8)."""
    b, m = ks[0].shape
    if m & (m - 1):
        raise ValueError(f"row length {m} is not a power of two")
    if b % _ROWS:
        raise ValueError(f"batch {b} is not a multiple of {_ROWS}")
    spec = pl.BlockSpec((_ROWS, m), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct((b, m), jnp.int32)
    return pl.pallas_call(
        _bitonic_kernel,
        grid=(b // _ROWS,),
        in_specs=[spec] * len(ks),
        out_specs=[spec] * len(ks),
        out_shape=[shape] * len(ks),
        interpret=interpret,
    )(*(k.astype(jnp.int32) for k in ks))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_sort3(
    k1: jax.Array, k2: jax.Array, k3: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return tuple(_pallas_sort_n((k1, k2, k3), interpret=interpret))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_sort2(
    k1: jax.Array, k2: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    return tuple(_pallas_sort_n((k1, k2), interpret=interpret))


def _env_hatches() -> Tuple[str, ...]:
    """Env hatches that shape a probe verdict — the probe cache keys on
    these so flipping a hatch mid-process (as tests do) re-probes instead
    of serving the verdict cached under the old env."""
    return (
        os.environ.get("TEXTBLAST_PALLAS", ""),
        os.environ.get("TEXTBLAST_NO_PALLAS", ""),
        os.environ.get("TEXTBLAST_PALLAS_INTERPRET", ""),
    )


@functools.lru_cache(maxsize=32)
def _probe_cached(env: Tuple[str, ...], backend: str) -> bool:
    del env  # participates only in the cache key
    if pltpu is None or backend == "cpu":
        return False
    try:
        with jax.ensure_compile_time_eval():
            x = jnp.zeros((_ROWS, 128), jnp.int32)
            jax.block_until_ready(pallas_sort3(x, x, x))
        return True
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("Pallas sort unavailable on %s: %s", backend, e)
        return False


def _probe_backend() -> bool:
    return _probe_cached(_env_hatches(), jax.default_backend())


def pallas_sort_supported() -> bool:
    """Whether the Pallas kernel can run here.  Env-dependent decisions are
    re-read on every call (only the backend lowering probe is cached), so a
    test or embedder toggling the env vars cannot be poisoned by a stale
    cached answer."""
    if not pallas_enabled():
        return False
    if _interpret_forced():
        return True
    return _probe_backend()


def _pallas_ok(b: int, m: int) -> bool:
    # Upper bound: m=16384 is silicon-proven (round 3); 32768 still fits the
    # ~8 VMEM row-copies the network needs, 65536 would not — those rows fall
    # back to lax.sort.
    return (
        pallas_sort_supported()
        and 128 <= m <= 32768
        and not (m & (m - 1))
        and b % _ROWS == 0
        and b > 0
    )


def _data_axis_size(mesh: Optional[Mesh]) -> Optional[int]:
    """Size of the ``data`` mesh axis rows are sharded over; 1 only when the
    whole program is single-device.  None when the mesh has no data axis or
    has other >1 axes alongside data=1 (shard_map over ``data`` would be
    ill-formed / a bare pallas call would need a GSPMD rule; callers then
    use ``lax.sort``, which partitions fine under GSPMD)."""
    if mesh is None:
        return 1
    size = dict(mesh.shape).get(_DATA_AXIS)
    if size == 1 and mesh.devices.size > 1:
        return None
    return size


def _sharded_sort(fn, mesh: Mesh, ks):
    """Run ``fn`` (a pallas sort over the local shard) under shard_map, rows
    sharded along the data axis, each device's shard VMEM-resident."""
    spec = P(_DATA_AXIS, None)
    n = len(ks)
    kwargs = dict(mesh=mesh, in_specs=(spec,) * n, out_specs=(spec,) * n)
    try:
        # Replication checking needs vma annotations pallas outputs don't
        # carry; rows are fully sharded, nothing is replicated — disable it.
        mapped = shard_map(fn, check_vma=False, **kwargs)
    except TypeError:  # pre-vma JAX spells it check_rep
        mapped = shard_map(fn, check_rep=False, **kwargs)
    return mapped(*ks)


def _dispatch(*ks) -> Tuple[jax.Array, ...]:
    interpret = _interpret_forced()
    return tuple(_pallas_sort_n(ks, interpret=interpret))


def sort3(
    k1: jax.Array,
    k2: jax.Array,
    k3: jax.Array,
    mesh: Optional[Mesh] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Lexicographic row sort: Pallas bitonic network on TPU (shard_mapped
    over ``mesh`` when given), ``lax.sort`` elsewhere."""
    b, m = k1.shape
    n_dev = _data_axis_size(mesh)
    if n_dev is not None and n_dev > 1:
        if b % n_dev == 0 and _pallas_ok(b // n_dev, m):
            return _sharded_sort(_dispatch, mesh, (k1, k2, k3))
    elif n_dev == 1 and _pallas_ok(b, m):
        return pallas_sort3(k1, k2, k3, interpret=_interpret_forced())
    return jax.lax.sort(
        (k1.astype(jnp.int32), k2.astype(jnp.int32), k3.astype(jnp.int32)),
        dimension=1,
        num_keys=3,
    )


def sort2(
    k1: jax.Array, k2: jax.Array, mesh: Optional[Mesh] = None
) -> Tuple[jax.Array, jax.Array]:
    """Row sort by key ``k1`` carrying ``k2``, deterministic within equal
    keys: ascending ``k2`` order.

    On TPU this is the VMEM bitonic network sorting the full ``(k1, k2)``
    pair.  Elsewhere, when int64 is live (``jax_enable_x64`` — the CPU
    backend enables it for exactly this), the pair is packed into ONE
    ``(k1 << 32) | k2`` int64 operand and sorted with the single-operand
    ``lax.sort``, which XLA:CPU runs ~4.4x faster than the two-operand
    comparator form (measured [9216, 512]: 188ms vs 837ms); unpacked order
    is (k1, then k2) — identical to the stable form for non-negative
    payloads, which every caller passes (iotas or byte lengths).  With x64
    off, the 1-key *stable* two-operand ``lax.sort`` is used."""
    b, m = k1.shape
    n_dev = _data_axis_size(mesh)
    if n_dev is not None and n_dev > 1:
        if b % n_dev == 0 and _pallas_ok(b // n_dev, m):
            return _sharded_sort(_dispatch, mesh, (k1, k2))
    elif n_dev == 1 and _pallas_ok(b, m):
        return pallas_sort2(k1, k2, interpret=_interpret_forced())
    if jax.config.jax_enable_x64:
        z = (k1.astype(jnp.int64) << 32) | k2.astype(jnp.int64)
        s = jax.lax.sort(z, dimension=1)
        return (
            (s >> 32).astype(jnp.int32),
            (s & jnp.int64(0xFFFFFFFF)).astype(jnp.int32),
        )
    return jax.lax.sort(
        (k1.astype(jnp.int32), k2.astype(jnp.int32)),
        dimension=1,
        num_keys=1,
        is_stable=True,
    )
