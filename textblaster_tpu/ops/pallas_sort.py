"""VMEM-resident lexicographic sort — the pipeline's hottest device primitive.

Every duplicate-detection statistic (GopherRepetition line/paragraph/n-gram
dups, FineWeb duplicate lines — gopher_rep.rs:86-196, fineweb_quality.rs:
149-185 equivalents) reduces to "sort per-row (validity, hash, payload)
triples along the row".  XLA's ``lax.sort`` runs its compare-exchange network
with HBM round-trips between passes; this Pallas kernel keeps each block of
rows resident in VMEM for the entire bitonic network, so the ~log²(m)/2
stages cost lane-shuffles (``pltpu.roll``) and VPU selects instead of HBM
bandwidth.

The network is a standard bitonic sorter: static Python loops over
``(size, stride)`` stages — everything unrolls at trace time, all shapes
static, no gathers (partner access is a pair of circular lane shifts selected
by a constant parity mask), which keeps the kernel inside Mosaic's supported
op set.

Rows are independent; the grid tiles the batch dimension.  Row length must be
a power of two (all duplicate tables in :mod:`.stats` are sized to powers of
two by ``pipeline._table_sizes``).

``sort3()`` transparently falls back to ``lax.sort`` off-TPU or if the Pallas
lowering probe fails, so CPU tests and degraded environments keep working.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import threading
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on all platforms; lowering is TPU-only.
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

logger = logging.getLogger(__name__)

__all__ = ["sort3", "pallas_sort3", "pallas_sort_supported", "pallas_allowed"]

_ROWS = 8  # sublane tile for int32

_tls = threading.local()


@contextlib.contextmanager
def pallas_allowed(allowed: bool):
    """Scope the Pallas fast path (default allowed).

    Mosaic ``pallas_call`` custom calls carry no GSPMD partitioning rule, so a
    program jitted with multi-device ``in_shardings`` must not contain them —
    the compiled pipeline traces its stages under ``pallas_allowed(False)``
    whenever it targets a >1-device mesh, falling back to ``lax.sort``."""
    prev = getattr(_tls, "allowed", True)
    _tls.allowed = allowed and prev
    try:
        yield
    finally:
        _tls.allowed = prev


def _lex_gt(a: Tuple[jax.Array, ...], b: Tuple[jax.Array, ...]) -> jax.Array:
    """Elementwise lexicographic ``a > b`` over equal-length key tuples."""
    gt = a[-1] > b[-1]
    for x, y in zip(reversed(a[:-1]), reversed(b[:-1])):
        gt = (x > y) | ((x == y) & gt)
    return gt


def _bitonic_kernel(*refs):
    n = len(refs) // 2
    in_refs, out_refs = refs[:n], refs[n:]
    m = in_refs[0].shape[-1]
    ks = tuple(r[:] for r in in_refs)

    # In-kernel lane index (Pallas kernels cannot capture host constants).
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    size = 2
    while size <= m:
        stride = size // 2
        while stride >= 1:
            # Per-lane masks for this stage (stage parameters are static).
            is_lower = (lane & stride) == 0  # partner is at i+stride
            asc = (lane & size) == 0

            # pltpu.roll requires non-negative shifts; left-roll by `stride`
            # is a right-roll by `m - stride`.
            partners = tuple(
                jnp.where(
                    is_lower,
                    pltpu.roll(k, shift=m - stride, axis=1),
                    pltpu.roll(k, shift=stride, axis=1),
                )
                for k in ks
            )
            lower = tuple(jnp.where(is_lower, k, p) for k, p in zip(ks, partners))
            upper = tuple(jnp.where(is_lower, p, k) for k, p in zip(ks, partners))
            # Select between the two bool comparisons with i1 bitwise logic:
            # Mosaic cannot lower `select_n` with bool *operands* at >1 lane
            # tile (arith.trunci vector<i8> -> vector<i1> is unsupported).
            swap = (asc & _lex_gt(lower, upper)) | (
                jnp.logical_not(asc) & _lex_gt(upper, lower)
            )
            ks = tuple(jnp.where(swap, p, k) for k, p in zip(ks, partners))
            stride //= 2
        size *= 2

    for o, k in zip(out_refs, ks):
        o[:] = k


def _pallas_sort_n(ks: Tuple[jax.Array, ...], interpret: bool = False):
    """Row-wise ascending lexicographic sort of int32 ``[B, m]`` key arrays
    (``m`` a power of two, ``B`` a multiple of 8)."""
    b, m = ks[0].shape
    if m & (m - 1):
        raise ValueError(f"row length {m} is not a power of two")
    if b % _ROWS:
        raise ValueError(f"batch {b} is not a multiple of {_ROWS}")
    spec = pl.BlockSpec((_ROWS, m), lambda i: (i, 0))
    shape = jax.ShapeDtypeStruct((b, m), jnp.int32)
    return pl.pallas_call(
        _bitonic_kernel,
        grid=(b // _ROWS,),
        in_specs=[spec] * len(ks),
        out_specs=[spec] * len(ks),
        out_shape=[shape] * len(ks),
        interpret=interpret,
    )(*(k.astype(jnp.int32) for k in ks))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_sort3(
    k1: jax.Array, k2: jax.Array, k3: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    return tuple(_pallas_sort_n((k1, k2, k3), interpret=interpret))


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_sort2(
    k1: jax.Array, k2: jax.Array, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    return tuple(_pallas_sort_n((k1, k2), interpret=interpret))


@functools.lru_cache(maxsize=1)
def pallas_sort_supported() -> bool:
    """Probe whether the Pallas kernel lowers and runs on this backend."""
    if os.environ.get("TEXTBLAST_NO_PALLAS"):
        return False
    if pltpu is None or jax.default_backend() == "cpu":
        return False
    try:
        x = jnp.zeros((_ROWS, 128), jnp.int32)
        jax.block_until_ready(pallas_sort3(x, x, x))
        return True
    except Exception as e:  # pragma: no cover - backend-specific
        logger.warning("Pallas sort unavailable on %s: %s", jax.default_backend(), e)
        return False


def _pallas_ok(b: int, m: int) -> bool:
    return (
        getattr(_tls, "allowed", True)
        and pallas_sort_supported()
        and m >= 128
        and not (m & (m - 1))
        and b % _ROWS == 0
    )


def sort3(
    k1: jax.Array, k2: jax.Array, k3: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Lexicographic row sort: Pallas bitonic network on TPU, ``lax.sort``
    elsewhere."""
    b, m = k1.shape
    if _pallas_ok(b, m):
        return pallas_sort3(k1, k2, k3)
    return jax.lax.sort(
        (k1.astype(jnp.int32), k2.astype(jnp.int32), k3.astype(jnp.int32)),
        dimension=1,
        num_keys=3,
    )


def sort2(k1: jax.Array, k2: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Row sort by key ``k1`` carrying ``k2``, deterministic within equal
    keys: ascending ``k2`` order.

    Off-TPU this is the 1-key *stable* ``lax.sort`` (callers pass ``k2``
    either already ascending per row — an iota — or as a payload whose
    within-run order is irrelevant); on TPU it is the VMEM bitonic network
    sorting the full ``(k1, k2)`` pair, which is equivalent up to within-run
    payload order (and exactly equal for iota payloads)."""
    b, m = k1.shape
    if _pallas_ok(b, m):
        return pallas_sort2(k1, k2)
    return jax.lax.sort(
        (k1.astype(jnp.int32), k2.astype(jnp.int32)),
        dimension=1,
        num_keys=1,
        is_stable=True,
    )
