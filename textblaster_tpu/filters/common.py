"""Shared helpers for the filter steps."""

from __future__ import annotations

from typing import List

__all__ = ["rust_lines", "fmt2", "fmt4", "rust_bool", "rust_float"]


def rust_lines(text: str) -> List[str]:
    """Split like Rust's ``str::lines()``: on ``\\n``, stripping one trailing
    ``\\r`` per line, with no trailing empty line for newline-terminated text.

    (Python's ``splitlines()`` also breaks on ``\\x0b``/``\\x85``/U+2028 etc.,
    which would diverge from the reference.)
    """
    if not text:
        return []
    parts = text.split("\n")
    if parts and parts[-1] == "":
        parts.pop()
    return [p[:-1] if p.endswith("\r") else p for p in parts]


def fmt2(v: float) -> str:
    """Rust ``{:.2}`` formatting."""
    return f"{v:.2f}"


def fmt4(v: float) -> str:
    """Rust ``{:.4}`` formatting."""
    return f"{v:.4f}"


def rust_bool(b: bool) -> str:
    """Rust ``{}`` Display for bool."""
    return "true" if b else "false"


def rust_float(v: float) -> str:
    """Rust ``{}`` Display for f64: shortest round-trip decimal, with integral
    values printed without the trailing ``.0`` Python's repr adds."""
    s = repr(float(v))
    if s.endswith(".0"):
        return s[:-2]
    return s
