"""Gopher quality heuristics filter.

Decision-for-decision re-implementation of ``GopherQualityFilter``
(``/root/reference/src/pipeline/filters/gopher_quality.rs:19-319``): nine
optional heuristics (``None`` disables each), reason strings with ``{:.2}``
ratios, and the reference's quirks — ``max_non_alpha_words_ratio`` actually
tests a *minimum alphabetic-word ratio* (gopher_quality.rs:277-284), hash and
ellipsis ratios share ``max_symbol_word_ratio`` (242-256), and ratio
denominators clamp to 1 (102, 128).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep
from ..utils.text import PUNCTUATION, split_into_words
from .common import fmt2, rust_lines

__all__ = ["GopherQualityFilter", "DEFAULT_STOP_WORDS"]

# gopher_quality.rs:10
DEFAULT_STOP_WORDS = ("the", "be", "to", "of", "and", "that", "have", "with")


class GopherQualityFilter(ProcessingStep):
    name = "GopherQualityFilter"

    def __init__(
        self,
        min_doc_words: Optional[int] = None,
        max_doc_words: Optional[int] = None,
        min_avg_word_length: Optional[float] = None,
        max_avg_word_length: Optional[float] = None,
        max_symbol_word_ratio: Optional[float] = None,
        max_bullet_lines_ratio: Optional[float] = None,
        max_ellipsis_lines_ratio: Optional[float] = None,
        max_non_alpha_words_ratio: Optional[float] = None,
        min_stop_words: Optional[int] = None,
        stop_words: Optional[Sequence[str]] = None,
    ) -> None:
        self.min_doc_words = min_doc_words
        self.max_doc_words = max_doc_words
        self.min_avg_word_length = min_avg_word_length
        self.max_avg_word_length = max_avg_word_length
        self.max_symbol_word_ratio = max_symbol_word_ratio
        self.max_bullet_lines_ratio = max_bullet_lines_ratio
        self.max_ellipsis_lines_ratio = max_ellipsis_lines_ratio
        self.max_non_alpha_words_ratio = max_non_alpha_words_ratio
        self.min_stop_words = min_stop_words
        self.stop_words: Set[str] = set(
            stop_words if stop_words is not None else DEFAULT_STOP_WORDS
        )

    def process(self, document: TextDocument) -> TextDocument:
        text = document.content
        words = split_into_words(text)
        n_total_words = len(words)

        # Non-symbol words: >=1 char outside the PUNCTUATION set
        # (gopher_quality.rs:80-85).
        non_symbol_words = [w for w in words if any(c not in PUNCTUATION for c in w)]
        n_non_symbol = len(non_symbol_words)

        avg_word_len = (
            sum(len(w) for w in non_symbol_words) / n_non_symbol if n_non_symbol else 0.0
        )

        n_total_calc = float(max(n_total_words, 1))  # gopher_quality.rs:102

        hash_ratio = text.count("#") / n_total_calc
        ellipsis_units = text.count("...") + text.count("…")
        ellipsis_ratio = ellipsis_units / n_total_calc

        lines = rust_lines(text)
        n_lines_calc = float(max(len(lines), 1))  # gopher_quality.rs:128
        bullet_lines = sum(
            1 for l in lines if l.lstrip().startswith(("•", "-"))
        )
        bullet_ratio = bullet_lines / n_lines_calc
        ellipsis_lines = sum(
            1 for l in lines if l.rstrip().endswith(("...", "…"))
        )
        ellipsis_lines_ratio = ellipsis_lines / n_lines_calc

        alpha_words = sum(1 for w in words if any(c.isalpha() for c in w))
        alpha_ratio = alpha_words / n_total_calc

        stop_word_count = sum(1 for w in words if w.lower() in self.stop_words)

        reasons: List[str] = []

        if self.min_doc_words is not None and n_non_symbol < self.min_doc_words:
            reasons.append(
                f"gopher_short_doc ({n_non_symbol} non-symbol words, "
                f"required {self.min_doc_words})"
            )
        if self.max_doc_words is not None and n_non_symbol > self.max_doc_words:
            reasons.append(
                f"gopher_long_doc ({n_non_symbol} non-symbol words, "
                f"max {self.max_doc_words})"
            )

        if self.min_avg_word_length is not None and avg_word_len < self.min_avg_word_length:
            suffix = (
                " - 0 non-symbol words"
                if n_non_symbol == 0 and self.min_avg_word_length > 0.0
                else ""
            )
            reasons.append(
                f"gopher_below_avg_threshold (avg len {fmt2(avg_word_len)}, "
                f"required {fmt2(self.min_avg_word_length)}{suffix})"
            )
        if (
            self.max_avg_word_length is not None
            and n_non_symbol > 0
            and avg_word_len > self.max_avg_word_length
        ):
            reasons.append(
                f"gopher_above_avg_threshold (avg len {fmt2(avg_word_len)}, "
                f"max {fmt2(self.max_avg_word_length)})"
            )

        if self.max_symbol_word_ratio is not None:
            if hash_ratio > self.max_symbol_word_ratio:
                reasons.append(
                    f"gopher_too_many_hashes (ratio {fmt2(hash_ratio)}, "
                    f"max {fmt2(self.max_symbol_word_ratio)})"
                )
            # Gopher re-uses max_symbol_word_ratio for ellipsis (rs:249-255).
            if ellipsis_ratio > self.max_symbol_word_ratio:
                reasons.append(
                    f"gopher_too_many_ellipsis_units (ratio {fmt2(ellipsis_ratio)}, "
                    f"max {fmt2(self.max_symbol_word_ratio)})"
                )

        if (
            self.max_bullet_lines_ratio is not None
            and bullet_ratio > self.max_bullet_lines_ratio
        ):
            reasons.append(
                f"gopher_too_many_bullets (ratio {fmt2(bullet_ratio)}, "
                f"max {fmt2(self.max_bullet_lines_ratio)})"
            )
        if (
            self.max_ellipsis_lines_ratio is not None
            and ellipsis_lines_ratio > self.max_ellipsis_lines_ratio
        ):
            reasons.append(
                f"gopher_too_many_end_ellipsis_lines (ratio {fmt2(ellipsis_lines_ratio)}, "
                f"max {fmt2(self.max_ellipsis_lines_ratio)})"
            )

        # Inverted naming quirk: this is a minimum-alpha-ratio test (rs:277-284).
        if (
            self.max_non_alpha_words_ratio is not None
            and alpha_ratio < self.max_non_alpha_words_ratio
        ):
            reasons.append(
                f"gopher_below_alpha_threshold (alpha ratio {fmt2(alpha_ratio)}, "
                f"required min {fmt2(self.max_non_alpha_words_ratio)})"
            )

        if (
            self.min_stop_words is not None
            and self.min_stop_words > 0
            and stop_word_count < self.min_stop_words
        ):
            reasons.append(
                f"gopher_too_few_stop_words (found {stop_word_count}, "
                f"required {self.min_stop_words})"
            )

        if reasons:
            reasons_string = "; ".join(reasons)
            document.metadata["gopher_quality_filter_status"] = "filtered"
            document.metadata["gopher_quality_filter_reasons"] = reasons_string
            raise DocumentFiltered(document, reasons_string)

        document.metadata["gopher_quality_filter_status"] = "passed"
        return document
