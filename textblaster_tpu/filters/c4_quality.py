"""C4 quality filter (mutating).

Re-implementation of ``C4QualityFilter``
(``/root/reference/src/pipeline/filters/c4_filters.rs:84-296``): document-level
early rejects (lorem ipsum / curly bracket), a per-line keep/drop loop with
citation removal, and a final sentence-count check on the *rewritten* content.
Line-drop counters are stamped into metadata keyed ``line-filter-*`` — only on
the filtered path (c4_filters.rs:281-283).
"""

from __future__ import annotations

import re
from typing import Dict, List

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep
from ..utils.text import split_into_sentences, split_into_words
from .common import rust_lines

__all__ = ["C4QualityFilter", "END_PUNCTUATION", "POLICY_SUBSTRINGS", "CITATION_RE"]

# c4_filters.rs:20
END_PUNCTUATION = (".", "!", "?", '"', "'", "”")
ELLIPSIS = "..."

# c4_filters.rs:24-31
POLICY_SUBSTRINGS = (
    "terms of use",
    "privacy policy",
    "cookie policy",
    "uses cookies",
    "use of cookies",
    "use cookies",
)

# Wikipedia-style citations like [1], [2, 3], [45] (c4_filters.rs:33).
CITATION_RE = re.compile(r"\[\d+(?:,\s*\d+)*\]")


class C4QualityFilter(ProcessingStep):
    name = "C4QualityFilter"

    def __init__(
        self,
        split_paragraph: bool,
        remove_citations: bool,
        filter_no_terminal_punct: bool,
        min_num_sentences: int,
        min_words_per_line: int,
        max_word_length: int,
        filter_lorem_ipsum: bool,
        filter_javascript: bool,
        filter_curly_bracket: bool,
        filter_policy: bool,
    ) -> None:
        self.split_paragraph = split_paragraph
        self.remove_citations = remove_citations
        self.filter_no_terminal_punct = filter_no_terminal_punct
        self.min_num_sentences = min_num_sentences
        self.min_words_per_line = min_words_per_line
        self.max_word_length = max_word_length
        self.filter_lorem_ipsum = filter_lorem_ipsum
        self.filter_javascript = filter_javascript
        self.filter_curly_bracket = filter_curly_bracket
        self.filter_policy = filter_policy

    def process(self, document: TextDocument) -> TextDocument:
        original = document.content
        lines = (
            rust_lines(original)
            if self.split_paragraph
            else split_into_sentences(original)
        )

        reasons: List[str] = []

        # Document-level early rejects (c4_filters.rs:166-187).
        if self.filter_lorem_ipsum and "lorem ipsum" in original.lower():
            reasons.append("lorem_ipsum")
        if self.filter_curly_bracket and ("{" in original or "}" in original):
            reasons.append("curly_bracket")

        if reasons:
            reasons_string = "; ".join(reasons)
            document.metadata["c4_filter_status"] = "filtered"
            document.metadata["c4_filter_reasons"] = reasons_string
            raise DocumentFiltered(document, reasons_string)

        line_stats: Dict[str, int] = {}
        kept_lines: List[str] = []

        for line in lines:
            current = line.strip()
            processed = CITATION_RE.sub("", current) if self.remove_citations else current

            line_l = processed.lower()
            words = split_into_words(processed)

            # Overlong word (c4_filters.rs:207-216).
            if self.max_word_length > 0 and any(
                len(w) > self.max_word_length for w in words
            ):
                line_stats["line-filter-too_long_word"] = (
                    line_stats.get("line-filter-too_long_word", 0) + 1
                )
                continue

            # Terminal punctuation; a line ending in "..." fails even though
            # '.' is terminal (c4_filters.rs:219-232).
            if self.filter_no_terminal_punct:
                ends_terminal = bool(processed) and processed[-1] in END_PUNCTUATION
                if not ends_terminal or processed.endswith(ELLIPSIS):
                    line_stats["line-filter-no_terminal_punc"] = (
                        line_stats.get("line-filter-no_terminal_punc", 0) + 1
                    )
                    continue

            # Minimum word count (c4_filters.rs:235-240).
            if self.min_words_per_line > 0 and len(words) < self.min_words_per_line:
                line_stats["line-filter-too_few_words"] = (
                    line_stats.get("line-filter-too_few_words", 0) + 1
                )
                continue

            # Javascript / policy lines are dropped without a counter
            # (c4_filters.rs:243-250).
            if self.filter_javascript and "javascript" in line_l:
                continue
            if self.filter_policy and any(p in line_l for p in POLICY_SUBSTRINGS):
                continue

            kept_lines.append(processed)

        # Rewrite content from kept lines (c4_filters.rs:258).
        document.content = "\n".join(kept_lines).strip()

        # Sentence count on the filtered content (c4_filters.rs:261-269).
        n_sentences = len(split_into_sentences(document.content))
        if self.min_num_sentences > 0 and n_sentences < self.min_num_sentences:
            reasons.append(
                f"too_few_sentences (found {n_sentences}, "
                f"required {self.min_num_sentences})"
            )

        if reasons:
            reasons_string = "; ".join(reasons)
            document.metadata["c4_filter_status"] = "filtered"
            document.metadata["c4_filter_reasons"] = reasons_string
            for key, value in line_stats.items():
                document.metadata[key] = str(value)
            raise DocumentFiltered(document, reasons_string)

        document.metadata["c4_filter_status"] = "passed"
        return document
