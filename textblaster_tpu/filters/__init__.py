"""Host-path processing steps (the parity oracle for the TPU kernel library).

Each filter reproduces the decision logic, metadata stamping, and reason-string
formats of its reference counterpart under
``/root/reference/src/pipeline/filters/`` bit-for-bit.  The TPU kernels in
:mod:`textblaster_tpu.ops` are validated against these implementations.
"""

from .c4_badwords import C4BadWordsFilter
from .c4_quality import C4QualityFilter
from .fineweb_quality import FineWebQualityFilter
from .gopher_quality import GopherQualityFilter
from .gopher_repetition import GopherRepetitionFilter
from .language import LanguageDetectionFilter
from .token_counter import TokenCounter

__all__ = [
    "C4QualityFilter",
    "C4BadWordsFilter",
    "FineWebQualityFilter",
    "GopherQualityFilter",
    "GopherRepetitionFilter",
    "LanguageDetectionFilter",
    "TokenCounter",
]
