"""FineWeb quality filter.

Re-implementation of ``FineWebQualityFilter``
(``/root/reference/src/pipeline/filters/fineweb_quality.rs:29-227``).
Sequential early-exit checks whose order is observable (first failure wins —
SURVEY.md §7 quirk #6).  The default stop-char set equals the reference's C4
set, *not* the Python original's CJK set (fineweb_quality.rs:25-26).  On the
empty-document path the metadata reason is ``"empty document"`` but the
outcome reason is ``"empty"`` (fineweb_quality.rs:79-89) — reproduced as-is.
On success no metadata is stamped (fineweb_quality.rs:225).
"""

from __future__ import annotations

from typing import Optional, Set

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep
from ..utils.text import find_duplicates, split_into_words
from .common import fmt4, rust_bool, rust_lines

__all__ = ["FineWebQualityFilter", "DEFAULT_STOP_CHARS"]

# fineweb_quality.rs:26 — deliberately the C4 END_PUNCTUATION set.
DEFAULT_STOP_CHARS = frozenset({".", "!", "?", '"', "'", "”"})


class FineWebQualityFilter(ProcessingStep):
    name = "FineWebQualityFilter"

    def __init__(
        self,
        line_punct_thr: float,
        line_punct_exclude_zero: bool,
        short_line_thr: float,
        short_line_length: int,
        char_duplicates_ratio: float,
        new_line_ratio: float,
        stop_chars: Optional[Set[str]] = None,
    ) -> None:
        self.line_punct_thr = line_punct_thr
        self.line_punct_exclude_zero = line_punct_exclude_zero
        self.stop_chars = (
            frozenset(stop_chars) if stop_chars is not None else DEFAULT_STOP_CHARS
        )
        self.short_line_thr = short_line_thr
        self.short_line_length = short_line_length
        self.char_duplicates_ratio = char_duplicates_ratio
        self.new_line_ratio = new_line_ratio

    def _fail(self, document: TextDocument, reason: str, outcome_reason: str = "") -> None:
        document.metadata["fineweb_filter_status"] = "filtered"
        document.metadata["fineweb_filter_reason"] = reason
        raise DocumentFiltered(document, outcome_reason or reason)

    def process(self, document: TextDocument) -> TextDocument:
        content = document.content
        lines = [l for l in rust_lines(content) if l.strip()]

        if not lines:
            # Quirk: metadata says "empty document", outcome reason is "empty".
            self._fail(document, "empty document", outcome_reason="empty")

        # 1. Ratio of lines ending with stop characters (rs:93-123).
        ending = sum(
            1
            for l in lines
            if l.rstrip() and l.rstrip()[-1] in self.stop_chars
        )
        line_punct_ratio = ending / len(lines)
        if line_punct_ratio < self.line_punct_thr and not (
            line_punct_ratio == 0.0 and self.line_punct_exclude_zero
        ):
            self._fail(
                document,
                f"line_punct_ratio: {fmt4(line_punct_ratio)} < threshold "
                f"{fmt4(self.line_punct_thr)} (exclude_zero: "
                f"{rust_bool(self.line_punct_exclude_zero)})",
            )

        # 2. Ratio of short lines (rs:126-146).
        short = sum(1 for l in lines if len(l) <= self.short_line_length)
        short_ratio = short / len(lines)
        if short_ratio > self.short_line_thr:
            self._fail(
                document,
                f"short_line_ratio: {fmt4(short_ratio)} > threshold "
                f"{fmt4(self.short_line_thr)}",
            )

        # 3. Character duplication ratio: duplicate-line *byte* length over
        #    newline-free *char* count (rs:149-185 + text.rs:203).
        total_chars = sum(1 for c in content if c != "\n")
        _, dup_bytes = find_duplicates(lines)
        char_dup_ratio = dup_bytes / total_chars if total_chars > 0 else 0.0
        if char_dup_ratio > self.char_duplicates_ratio:
            self._fail(
                document,
                f"char_dup_ratio: {fmt4(char_dup_ratio)} > threshold "
                f"{fmt4(self.char_duplicates_ratio)}",
            )

        # 4. Newline/word ratio (rs:188-223).
        words = split_into_words(content)
        new_lines = content.count("\n")
        if not words:
            if new_lines > 0:
                self._fail(document, "list_ratio_no_words (newlines present but no words)")
        else:
            list_ratio = new_lines / len(words)
            if list_ratio > self.new_line_ratio:
                self._fail(
                    document,
                    f"list_ratio: {fmt4(list_ratio)} > threshold "
                    f"{fmt4(self.new_line_ratio)}",
                )

        return document
