"""Gopher repetition filter.

Re-implementation of ``GopherRepetitionFilter``
(``/root/reference/src/pipeline/filters/gopher_rep.rs:12-221``).  Reproduces
the bytes-vs-chars quirk: duplicate lengths are **UTF-8 byte** sums
(text.rs:203,230,252) while the denominator is the trimmed **char** count
clamped to 1 (gopher_rep.rs:58) — see SURVEY.md §7.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep
from ..utils.text import find_duplicates, ngram_dup_stats
from .common import fmt2

__all__ = ["GopherRepetitionFilter"]

_PARAGRAPH_RE = re.compile(r"\n{2,}")  # gopher_rep.rs:40
_LINE_RE = re.compile(r"\n+")  # gopher_rep.rs:41


class GopherRepetitionFilter(ProcessingStep):
    name = "GopherRepetitionFilter"

    def __init__(
        self,
        dup_line_frac: Optional[float] = None,
        dup_para_frac: Optional[float] = None,
        dup_line_char_frac: Optional[float] = None,
        dup_para_char_frac: Optional[float] = None,
        top_n_grams: Sequence[Tuple[int, float]] = (),
        dup_n_grams: Sequence[Tuple[int, float]] = (),
    ) -> None:
        self.dup_line_frac = dup_line_frac
        self.dup_para_frac = dup_para_frac
        self.dup_line_char_frac = dup_line_char_frac
        self.dup_para_char_frac = dup_para_char_frac
        self.top_n_grams = [(int(n), float(f)) for n, f in top_n_grams]
        self.dup_n_grams = [(int(n), float(f)) for n, f in dup_n_grams]

    def process(self, document: TextDocument) -> TextDocument:
        trimmed = document.content.strip()
        text_char_len = float(max(len(trimmed), 1))  # gopher_rep.rs:58

        if not trimmed:
            document.metadata["gopher_repetition_filter_status"] = "filtered"
            document.metadata["gopher_repetition_filter_reason"] = "skipping empty content"
            raise DocumentFiltered(document, "skipping empty content")

        reasons: List[str] = []

        paragraphs = _PARAGRAPH_RE.split(trimmed)
        para_dup_elems, para_dup_bytes = find_duplicates(paragraphs)
        para_len = float(max(len(paragraphs), 1))

        ratio = para_dup_elems / para_len
        if self.dup_para_frac is not None and ratio > self.dup_para_frac:
            reasons.append(
                f"dup_para_frac (ratio {fmt2(ratio)}, max {fmt2(self.dup_para_frac)})"
            )

        ratio = para_dup_bytes / text_char_len
        if self.dup_para_char_frac is not None and ratio > self.dup_para_char_frac:
            reasons.append(
                f"dup_para_char_frac (ratio {fmt2(ratio)}, "
                f"max {fmt2(self.dup_para_char_frac)})"
            )

        lines = _LINE_RE.split(trimmed)
        line_dup_elems, line_dup_bytes = find_duplicates(lines)
        line_len = float(max(len(lines), 1))

        ratio = line_dup_elems / line_len
        if self.dup_line_frac is not None and ratio > self.dup_line_frac:
            reasons.append(
                f"dup_line_frac (ratio {fmt2(ratio)}, max {fmt2(self.dup_line_frac)})"
            )

        ratio = line_dup_bytes / text_char_len
        if self.dup_line_char_frac is not None and ratio > self.dup_line_char_frac:
            reasons.append(
                f"dup_line_char_frac (ratio {fmt2(ratio)}, "
                f"max {fmt2(self.dup_line_char_frac)})"
            )

        top_stats, dup_stats = ngram_dup_stats(
            trimmed,
            [n for n, _ in self.top_n_grams],
            [n for n, _ in self.dup_n_grams],
        )

        for n, thr in self.top_n_grams:
            ratio = top_stats[n] / text_char_len
            if n > 0 and ratio > thr:
                reasons.append(f"top_{n}_gram (ratio {fmt2(ratio)}, max {fmt2(thr)})")

        for n, thr in self.dup_n_grams:
            ratio = dup_stats[n] / text_char_len
            if n > 0 and ratio > thr:
                reasons.append(
                    f"duplicated_{n}_n_grams (ratio {fmt2(ratio)}, max {fmt2(thr)})"
                )

        if reasons:
            document.metadata["gopher_repetition_filter_status"] = "filtered"
            reasons_string = "; ".join(reasons)
            document.metadata["gopher_repetition_filter_reasons"] = reasons_string
            raise DocumentFiltered(document, reasons_string)

        document.metadata["gopher_repetition_filter_status"] = "passed"
        return document
