"""Token counting step (never filters).

Re-implementation of ``TokenCounter``
(``/root/reference/src/pipeline/token/token_counter.rs:8-43``): loads a
HuggingFace tokenizer at build time, encodes content *with* special tokens,
and stamps ``metadata["token_count"]``.

Loading resolution order (the reference only supports hub fetch,
token_counter.rs:14; this build adds offline paths first since TPU pods are
often egress-less):

1. a local path to a ``tokenizer.json`` file or a directory containing one;
2. the HuggingFace hub cache / network via ``tokenizers.Tokenizer.from_pretrained``.

A load failure raises ``UnexpectedError("Error in loading tokenizer")`` at
construction, matching the reference's build-time failure surface
(worker_logic.rs:115-122 panics on it).
"""

from __future__ import annotations

import os

from ..data_model import TextDocument
from ..errors import UnexpectedError
from ..executor import ProcessingStep

__all__ = ["TokenCounter"]


class TokenCounter(ProcessingStep):
    name = "TokenCounter"

    def __init__(self, tokenizer_name: str) -> None:
        try:
            from tokenizers import Tokenizer

            path = tokenizer_name
            if os.path.isdir(path):
                path = os.path.join(path, "tokenizer.json")
            if os.path.isfile(path):
                self._tokenizer = Tokenizer.from_file(path)
            else:
                self._tokenizer = Tokenizer.from_pretrained(tokenizer_name)
        except Exception as e:
            raise UnexpectedError("Error in loading tokenizer") from e

    def process(self, document: TextDocument) -> TextDocument:
        try:
            encoding = self._tokenizer.encode(document.content, add_special_tokens=True)
        except Exception as e:
            raise UnexpectedError(str(e)) from e
        document.metadata["token_count"] = str(len(encoding.tokens))
        return document
