"""Token counting step (never filters).

Re-implementation of ``TokenCounter``
(``/root/reference/src/pipeline/token/token_counter.rs:8-43``): loads a
HuggingFace tokenizer at build time, encodes content *with* special tokens,
and stamps ``metadata["token_count"]``.

Loading resolution order (the reference only supports hub fetch,
token_counter.rs:14; this build adds offline paths first since TPU pods are
often egress-less):

1. a local path to a ``tokenizer.json`` file or a directory containing one;
2. a local ``merges.txt`` (GPT-2 byte-level BPE) counted by the native C++
   core (``textblaster_tpu/native``) — no vocab ids are needed for a count;
3. the HuggingFace hub cache / network via ``tokenizers.Tokenizer.from_pretrained``;
4. a vendored stand-in under ``textblaster_tpu/data/tokenizers/<name>/`` —
   an in-repo-trained byte-level BPE shipped so the default config's
   ``TokenCounter(gpt2)`` executes on egress-less machines (see the README
   beside it; hub/cache wins whenever reachable).

A load failure raises ``UnexpectedError("Error in loading tokenizer")`` at
construction, matching the reference's build-time failure surface
(worker_logic.rs:115-122 panics on it).
"""

from __future__ import annotations

import os

from ..data_model import TextDocument
from ..errors import UnexpectedError
from ..executor import ProcessingStep

__all__ = ["TokenCounter"]


class TokenCounter(ProcessingStep):
    name = "TokenCounter"

    def __init__(self, tokenizer_name: str) -> None:
        self._tokenizer = None
        self._bpe = None
        #: True when the in-repo-trained stand-in replaced an unreachable hub
        #: tokenizer: counts then differ from the reference's, and every
        #: document is stamped so divergent runs are identifiable
        #: (ADVICE r4).
        self._standin = False
        try:
            json_path = tokenizer_name
            merges_path = None
            if os.path.isdir(tokenizer_name):
                json_path = os.path.join(tokenizer_name, "tokenizer.json")
                merges_path = os.path.join(tokenizer_name, "merges.txt")
            elif tokenizer_name.endswith("merges.txt"):
                json_path = None
                merges_path = tokenizer_name
            if json_path is not None and os.path.isfile(json_path):
                from tokenizers import Tokenizer

                self._tokenizer = Tokenizer.from_file(json_path)
            elif merges_path is not None and os.path.isfile(merges_path):
                # Byte-level BPE counting on the native core — the egress-less
                # path (vocab ids are not needed for a token *count*).
                from ..native import BpeCounter

                self._bpe = BpeCounter.from_file(merges_path)
            else:
                from tokenizers import Tokenizer

                try:
                    self._tokenizer = Tokenizer.from_pretrained(tokenizer_name)
                except Exception:
                    vendored = os.path.join(
                        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "data",
                        "tokenizers",
                        tokenizer_name,
                        "tokenizer.json",
                    )
                    if not os.path.isfile(vendored):
                        raise
                    import logging

                    logging.getLogger(__name__).warning(
                        "tokenizer %r unavailable from hub/cache; using the "
                        "vendored stand-in at %s (counts differ from the hub "
                        "tokenizer — see its README)",
                        tokenizer_name,
                        vendored,
                    )
                    self._tokenizer = Tokenizer.from_file(vendored)
                    self._standin = True
                    from ..utils.metrics import METRICS

                    METRICS.inc("worker_tokenizer_standin_total")
        except Exception as e:
            raise UnexpectedError("Error in loading tokenizer") from e

    def process(self, document: TextDocument) -> TextDocument:
        try:
            if self._bpe is not None:
                count = self._bpe.count(document.content)
            else:
                encoding = self._tokenizer.encode(
                    document.content, add_special_tokens=True
                )
                count = len(encoding.tokens)
        except Exception as e:
            raise UnexpectedError(str(e)) from e
        document.metadata["token_count"] = str(count)
        if self._standin:
            # Not a reference metadata key: deliberately extra so downstream
            # consumers can tell stand-in counts from hub-gpt2 counts.
            document.metadata["token_count_tokenizer"] = "vendored-standin"
        return document
