"""C4 bad-words filter.

Re-implementation of ``C4BadWordsFilter``
(``/root/reference/src/pipeline/filters/c4_filters.rs:298-552``):
language-keyed LDNOOBW blocklists with an on-disk cache, lazily compiled into
one case-insensitive alternation regex per language (CJK languages without
word-boundary anchors — c4_filters.rs:431-439), and a seeded keep-fraction.

RNG parity note: the reference draws ``f32`` from a *shared* Rust ``StdRng``
stream (ChaCha12, c4_filters.rs:306-309), which makes its keep decisions
depend on the order documents happen to reach the worker — nondeterministic
under queue delivery.  This build renegotiates to something strictly
stronger: with ``seed`` set, each document draws from
``sha256(seed, doc.id)``, so the decision is a pure function of the document
— identical across host/device backends, batch orderings, and resumed runs
(the distributional property, uniform keep at ``keep_fraction``, is
preserved; the renegotiation SURVEY.md §7 anticipates).  With ``seed`` unset
the draw falls back to an unseeded shared stream, nondeterministic like the
reference's default.

Network note: the reference downloads lists over HTTP at first use and vendors
none (c4_filters.rs:354-412) — offline, it supports zero of the 28 languages.
This build ships vendored LDNOOBW lists for ``da`` and ``en`` (authored-list
redistribution for the remaining 26 is neither possible offline nor required
for parity: the same lazy download + on-disk cache covers them exactly as the
reference's does, and ``cache_base_path`` lets deployments pre-seed every
language from a mirror).
"""

from __future__ import annotations

import hashlib
import os
import random
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Optional

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep

__all__ = [
    "C4BadWordsFilter",
    "C4BadWordsParams",
    "BADWORDS_LANGS",
    "load_local_badwords",
]

_EN_BADWORDS_URL = (
    "https://raw.githubusercontent.com/LDNOOBW/List-of-Dirty-Naughty-Obscene-"
    "and-Otherwise-Bad-Words/25e679f03d96baa721cde20db9944649e8d0a844/en"
)
_BADWORDS_URL = (
    "https://raw.githubusercontent.com/LDNOOBW/List-of-Dirty-Naughty-Obscene-"
    "and-Otherwise-Bad-Words/5faf2ba42d7b1c0977169ec3611df25a3c08eb13/"
)

# c4_filters.rs:38-67
BADWORDS_LANGS = (
    "ar", "cs", "da", "de", "en", "eo", "es", "fa", "fi", "fil", "fr",
    "fr-CA-u-sd-caqc", "hi", "hu", "it", "ja", "kab", "ko", "nl", "no", "pl",
    "pt", "ru", "sv", "th", "tlh", "tr", "zh",
)

_CJK_LANGS = ("ja", "th", "zh")  # c4_filters.rs:70

# Vendored lists shipped with the package (zero-egress environments).
_VENDORED_DIR = Path(__file__).resolve().parent.parent / "data" / "c4_badwords"


@dataclass
class C4BadWordsParams:
    """Parameters (reference ``config/pipeline.rs:260-268``)."""

    keep_fraction: float = 0.0
    fail_on_missing_language: bool = True
    seed: Optional[int] = None
    default_language: str = "en"
    cache_base_path: Optional[Path] = None
    extra: Dict[str, str] = field(default_factory=dict)


class _BadwordsError(Exception):
    def __init__(self, reason: str) -> None:
        super().__init__(reason)
        self.reason = reason


def local_badwords_path(
    lang: str, cache_base_path: Optional[Path] = None
) -> Path:
    """The path ``load_local_badwords`` would read: the cache-dir file if it
    exists, else the vendored file (which may also not exist)."""
    cache_dir = (
        Path(cache_base_path) if cache_base_path else Path("data") / "c4_badwords"
    )
    cached = cache_dir / lang
    return cached if cached.exists() else _VENDORED_DIR / lang


def load_local_badwords(
    lang: str, cache_base_path: Optional[Path] = None
) -> Optional[list]:
    """The language's word list from local sources only (cache dir, then the
    vendored package data) — no network.  None if unavailable; [] if the list
    exists but is empty.  Used by the device kernel builder
    (:mod:`textblaster_tpu.ops.badwords`), which must not trigger downloads
    at trace time."""
    if lang not in BADWORDS_LANGS:
        return None
    candidate = local_badwords_path(lang, cache_base_path)
    if candidate.exists():
        try:
            content = candidate.read_text(encoding="utf-8")
        except OSError:
            return None
        return [w.strip() for w in content.splitlines() if w.strip()]
    return None


class C4BadWordsFilter(ProcessingStep):
    name = "C4BadWordsFilter"

    def __init__(self, params: C4BadWordsParams) -> None:
        self.params = params
        self._regex_cache: Dict[str, Optional[re.Pattern]] = {}
        self._rng = random.Random(params.seed)

    # c4_filters.rs:318-454
    def _get_badwords(self, lang: str) -> Optional[re.Pattern]:
        if lang in self._regex_cache:
            return self._regex_cache[lang]

        if lang not in BADWORDS_LANGS:
            if self.params.fail_on_missing_language:
                raise _BadwordsError(
                    f"There is no badwords list available for '{lang}'. "
                    "Set fail_on_missing_language=False to continue anyway."
                )
            return None

        # Same resolution as the device-table builder (local_badwords_path):
        # cache file first, vendored copy second, download last.
        source = local_badwords_path(lang, self.params.cache_base_path)
        if source.exists():
            try:
                words_content = source.read_text(encoding="utf-8")
            except OSError as e:
                raise _BadwordsError(f"I/O error: {e}") from e
        else:
            cache_dir = (
                Path(self.params.cache_base_path)
                if self.params.cache_base_path
                else Path("data") / "c4_badwords"
            )
            words_content = self._download(lang, cache_dir, cache_dir / lang)

        badwords = [w.strip() for w in words_content.splitlines()]
        badwords = [w for w in badwords if w]
        if not badwords:
            # Empty list: behave as if none was available (c4_filters.rs:420-426).
            self._regex_cache[lang] = None
            return None

        escaped = [re.escape(w) for w in badwords]
        if lang in _CJK_LANGS:
            pattern = "(?i)(" + "|".join(escaped) + ")"
        else:
            pattern = r"(?i)(?:\W|^)(" + "|".join(escaped) + r")(?:\W|$)"
        try:
            compiled = re.compile(pattern)
        except re.error as e:
            raise _BadwordsError(
                f"Failed to compile regex for lang '{lang}': {e}"
            ) from e
        self._regex_cache[lang] = compiled
        return compiled

    def _download(self, lang: str, cache_dir: Path, cache_file: Path) -> str:
        url = _EN_BADWORDS_URL if lang == "en" else _BADWORDS_URL + lang
        try:
            os.makedirs(cache_dir, exist_ok=True)
        except OSError as e:
            raise _BadwordsError(f"I/O error: {e}") from e
        try:
            from urllib.request import urlopen

            with urlopen(url, timeout=15) as resp:  # noqa: S310
                if resp.status != 200:
                    raise _BadwordsError(
                        f"Failed to download badwords for lang '{lang}' from "
                        f"'{url}'. Status: {resp.status}"
                    )
                content = resp.read().decode("utf-8")
        except _BadwordsError:
            raise
        except Exception as e:
            raise _BadwordsError(
                f"Failed to download badwords for lang '{lang}' from '{url}': {e}"
            ) from e
        try:
            cache_file.write_text(content, encoding="utf-8")
        except OSError as e:
            raise _BadwordsError(f"I/O error: {e}") from e
        return content

    def _keep_draw(self, doc_id: str) -> float:
        """Uniform [0,1) draw deciding keep-by-fraction for one document.

        Seeded runs hash (seed, doc id) so the decision is order-independent —
        a pure host run, the device-prefiltered path, and a checkpoint resume
        all agree (see the module docstring's RNG parity note)."""
        if self.params.seed is None:
            return self._rng.random()
        h = hashlib.sha256(
            f"{self.params.seed}:{doc_id}".encode("utf-8")
        ).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def process(self, document: TextDocument) -> TextDocument:
        lang = document.metadata.get("language", self.params.default_language)

        try:
            badwords_re = self._get_badwords(lang)
        except _BadwordsError as e:
            document.metadata["c4_badwords_filter_status"] = "filtered"
            document.metadata["c4_badwords_filter_reason"] = e.reason
            raise DocumentFiltered(document, e.reason) from e

        if badwords_re is None:
            document.metadata["c4_badwords_filter_status"] = "passed_no_regex"
            return document

        if badwords_re.search(document.content):
            if self.params.keep_fraction > 0.0 and self._keep_draw(document.id) < self.params.keep_fraction:
                document.metadata["c4_badwords_filter_status"] = "passed_kept_by_fraction"
                return document
            reason = "document_removed_with_badwords"
            document.metadata["c4_badwords_filter_status"] = "filtered"
            document.metadata["c4_badwords_filter_reason"] = reason
            raise DocumentFiltered(document, reason)

        document.metadata["c4_badwords_filter_status"] = "passed"
        return document
