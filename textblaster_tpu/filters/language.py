"""Language detection filter.

Re-implementation of ``LanguageDetectionFilter``
(``/root/reference/src/pipeline/filters/language_filter.rs:7-94``), backed by
the framework's own statistical model (:mod:`textblaster_tpu.models.langid`)
over the same hardcoded 5-language candidate set.  Reproduces:

* detected language + confidence always stamped into metadata, even on the
  filtered path (language_filter.rs:51-57; SURVEY.md §7 quirk #11);
* unknown ISO codes in ``allowed_languages`` silently dropped
  (language_filter.rs:14-21);
* reason strings verbatim, including the ``{:?}``-quoted language list and the
  reference's "not satified" typo (language_filter.rs:66-77).

Unlike the reference, the detector is built once per process, not per document
(a per-doc hot-path cost called out in SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import List, Sequence

from ..data_model import TextDocument
from ..errors import DocumentFiltered
from ..executor import ProcessingStep
from ..models.langid import ISO_TO_NAME, NAME_TO_ISO, get_model
from .common import rust_float

__all__ = ["LanguageDetectionFilter"]


class LanguageDetectionFilter(ProcessingStep):
    name = "LanguageDetectionFilter"

    def __init__(self, min_confidence: float, allowed_languages: Sequence[str]) -> None:
        self.min_confidence = min_confidence
        # ISO-639-3 codes; unknown codes are dropped like the reference's
        # filter_map (language_filter.rs:14-21).
        self.allowed_languages: List[str] = [
            code for code in allowed_languages if code in ISO_TO_NAME
        ]
        self._model = get_model()

    def process(self, document: TextDocument) -> TextDocument:
        detection = self._model.detect(document.content)

        if detection is None:
            reason = "Language could not be confidently detected"
            raise DocumentFiltered(document, reason)

        lang_name, confidence = detection
        document.metadata["Detected language"] = lang_name
        document.metadata["Detected language confidence"] = rust_float(confidence)

        if NAME_TO_ISO[lang_name] not in self.allowed_languages:
            joined = "; ".join(self.allowed_languages)
            # {:?} on the joined String adds quotes (language_filter.rs:66-69).
            reason = f'Document is not any of the following languages: "{joined}"'
            raise DocumentFiltered(document, reason)

        if confidence < self.min_confidence:
            # "satified" typo preserved from language_filter.rs:75-78.
            reason = (
                f"Language detection confidence is not satified: "
                f"{rust_float(confidence)} < {rust_float(self.min_confidence)}"
            )
            raise DocumentFiltered(document, reason)

        return document
