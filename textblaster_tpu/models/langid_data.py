"""Training text for the language-ID trigram profiles.

The reference uses lingua's shipped statistical models
(``/root/reference/src/pipeline/filters/language_filter.rs:39-46``); those
tables cannot be vendored here, so the framework trains its own profiles from
this module: original prose authored for this project in each candidate
language (everyday/news/nature/practical registers), chosen to exercise the
orthography that separates the close Scandinavian pairs — Danish 'af/øj/ej/
-tion', Bokmål 'av/øy/ei/-sjon', Nynorsk 'ikkje/kva/ein/-inga', Swedish
'och/ä/inte/-ning'.

Kept deliberately disjoint from the labeled evaluation fixture
(``tests/data/langid_corpus.tsv``) so the agreement number measured there is
out-of-sample.
"""

_TRAIN_TEXT_1 = {
    "English": """
The kitchen smelled of fresh bread when the children came home from school.
In autumn the forest turns red and gold, and the air grows cold at night.
A small boat crossed the bay while the sun set behind the islands.
The engineers checked every bolt on the bridge before it opened to traffic.
Most shops close early on Sundays, so people buy their groceries on Saturday.
He has worked as a carpenter for thirty years and still enjoys the craft.
The weather forecast promises sunshine tomorrow with a light breeze from the west.
She borrowed three books from the library and read them all in one week.
Onions should be fried slowly in butter until they turn soft and golden.
The city council plans to build a new swimming pool next to the school.
Trains run every ten minutes during the day and every half hour at night.
Their grandmother grew roses and tomatoes in the little garden behind the house.
The meeting lasted two hours, but no decision was reached in the end.
Fishermen set out before dawn when the sea was calm and quiet.
The new phone costs far too much, so I will keep my old one.
Snow fell all night, and by morning the roads were white and silent.
A good night's sleep matters more for your health than most people think.
They painted the fence green and planted flowers along the narrow path.
The teacher asked the pupils to write a short story about the summer.
Prices went up again this month, mainly because fuel became more expensive.
The concert hall was full, and the audience clapped for several minutes.
He missed the last bus and had to walk the whole way home in the rain.
Wash the vegetables carefully and cut them into thin slices before serving.
The old clock on the wall has not worked since last winter.
Tourists come here in summer to hike in the mountains and swim in the lakes.
The newspaper wrote about a farmer who found a silver coin in his field.
Every spring the birds return and build their nests under the roof.
The doctor told him to rest for a week and drink plenty of water.
Our neighbours moved to the countryside because the city became too loud.
The factory employs two hundred people and exports machines to many countries.
She plays the violin in the evenings, and the music drifts across the yard.
Remember to lock the door and turn off the lights before you leave.
The ferry was cancelled because of the storm, so we stayed another night.
His speech was short but honest, and people liked it very much.
A cat sat on the windowsill watching the rain run down the glass.
The bakery opens at six, and the smell of bread fills the whole street.
They have been friends since childhood and still meet every Friday.
The museum keeps old tools, photographs and letters from the fishing villages.
Water boils faster with a lid on the pot, which saves energy.
The referee stopped the match twice because the fog grew too thick.
""",
    "Danish": """
Køkkenet duftede af friskbagt brød, da børnene kom hjem fra skole.
Om efteråret bliver skoven rød og gylden, og luften er kold om natten.
En lille båd sejlede over bugten, mens solen gik ned bag øerne.
Ingeniørerne efterså hver eneste bolt på broen, før den blev åbnet for trafik.
De fleste butikker lukker tidligt om søndagen, så folk handler ind om lørdagen.
Han har arbejdet som tømrer i tredive år og holder stadig af sit håndværk.
Vejrudsigten lover solskin i morgen med en let vind fra vest.
Hun lånte tre bøger på biblioteket og læste dem alle på en uge.
Løg skal steges langsomt i smør, til de bliver bløde og gyldne.
Kommunen planlægger at bygge en ny svømmehal ved siden af skolen.
Togene kører hvert tiende minut om dagen og hver halve time om natten.
Deres bedstemor dyrkede roser og tomater i den lille have bag huset.
Mødet varede to timer, men der blev ikke truffet nogen beslutning til sidst.
Fiskerne tog af sted før daggry, mens havet var roligt og stille.
Den nye telefon koster alt for meget, så jeg beholder min gamle.
Sneen faldt hele natten, og om morgenen lå vejene hvide og tavse.
En god nats søvn betyder mere for helbredet, end de fleste tror.
De malede hegnet grønt og plantede blomster langs den smalle sti.
Læreren bad eleverne skrive en lille historie om sommeren.
Priserne steg igen i denne måned, især fordi brændstof blev dyrere.
Koncertsalen var fyldt, og publikum klappede i flere minutter.
Han nåede ikke den sidste bus og måtte gå hele vejen hjem i regnen.
Skyl grøntsagerne omhyggeligt, og skær dem i tynde skiver før servering.
Det gamle ur på væggen har ikke virket siden sidste vinter.
Turister kommer hertil om sommeren for at vandre i bjergene og bade i søerne.
Avisen skrev om en landmand, der fandt en sølvmønt på sin mark.
Hvert forår vender fuglene tilbage og bygger rede under taget.
Lægen sagde, at han skulle hvile sig en uge og drikke rigeligt med vand.
Vores naboer flyttede på landet, fordi byen blev for larmende.
Fabrikken beskæftiger to hundrede mennesker og eksporterer maskiner til mange lande.
Hun spiller violin om aftenen, og musikken driver hen over gården.
Husk at låse døren og slukke lyset, inden du går.
Færgen blev aflyst på grund af stormen, så vi blev der en nat mere.
Hans tale var kort, men ærlig, og folk kunne rigtig godt lide den.
En kat sad i vindueskarmen og så regnen løbe ned ad ruden.
Bageriet åbner klokken seks, og duften af brød fylder hele gaden.
De har været venner siden barndommen og mødes stadig hver fredag.
Museet opbevarer gammelt værktøj, fotografier og breve fra fiskerlejerne.
Vandet koger hurtigere med låg på gryden, og det sparer energi.
Dommeren afbrød kampen to gange, fordi tågen blev for tæt.
Informationen findes på stationen, og billetter kan købes i automaten.
Situationen i organisationen krævede en hurtig løsning af bestyrelsen.
""",
    "Swedish": """
Köket doftade av nybakat bröd när barnen kom hem från skolan.
På hösten blir skogen röd och gyllene, och luften är kall om natten.
En liten båt seglade över viken medan solen gick ner bakom öarna.
Ingenjörerna kontrollerade varje bult på bron innan den öppnades för trafik.
De flesta affärer stänger tidigt på söndagar, så folk handlar på lördagen.
Han har arbetat som snickare i trettio år och tycker fortfarande om sitt hantverk.
Väderprognosen lovar solsken i morgon med en svag vind från väster.
Hon lånade tre böcker på biblioteket och läste alla på en vecka.
Lök ska stekas långsamt i smör tills den blir mjuk och gyllene.
Kommunen planerar att bygga en ny simhall bredvid skolan.
Tågen går var tionde minut på dagen och varje halvtimme på natten.
Deras mormor odlade rosor och tomater i den lilla trädgården bakom huset.
Mötet pågick i två timmar, men inget beslut fattades till slut.
Fiskarna gav sig av före gryningen medan havet låg lugnt och stilla.
Den nya telefonen kostar alldeles för mycket, så jag behåller min gamla.
Snön föll hela natten, och på morgonen låg vägarna vita och tysta.
En god natts sömn betyder mer för hälsan än de flesta tror.
De målade staketet grönt och planterade blommor längs den smala stigen.
Läraren bad eleverna skriva en kort berättelse om sommaren.
Priserna steg igen den här månaden, främst för att bränslet blev dyrare.
Konsertsalen var fullsatt, och publiken applåderade i flera minuter.
Han missade sista bussen och fick gå hela vägen hem i regnet.
Skölj grönsakerna noggrant och skär dem i tunna skivor före servering.
Den gamla klockan på väggen har inte fungerat sedan i vintras.
Turister kommer hit på sommaren för att vandra i fjällen och bada i sjöarna.
Tidningen skrev om en bonde som hittade ett silvermynt på sin åker.
Varje vår kommer fåglarna tillbaka och bygger bo under taket.
Läkaren sade åt honom att vila en vecka och dricka mycket vatten.
Våra grannar flyttade ut på landet eftersom staden blev för högljudd.
Fabriken sysselsätter tvåhundra personer och exporterar maskiner till många länder.
Hon spelar fiol på kvällarna, och musiken svävar över gården.
Kom ihåg att låsa dörren och släcka lamporna innan du går.
Färjan ställdes in på grund av stormen, så vi stannade en natt till.
Hans tal var kort men ärligt, och folk tyckte mycket om det.
En katt satt i fönstret och tittade på regnet som rann nerför rutan.
Bageriet öppnar klockan sex, och doften av bröd fyller hela gatan.
De har varit vänner sedan barndomen och träffas fortfarande varje fredag.
Museet bevarar gamla verktyg, fotografier och brev från fiskelägena.
Vattnet kokar snabbare med lock på kastrullen, vilket sparar energi.
Domaren avbröt matchen två gånger eftersom dimman blev för tät.
Människor säger att det är något särskilt med ljuset här uppe.
""",
    "Bokmal": """
Kjøkkenet luktet nybakt brød da barna kom hjem fra skolen.
Om høsten blir skogen rød og gyllen, og lufta er kald om natta.
En liten båt seilte over bukta mens sola gikk ned bak øyene.
Ingeniørene sjekket hver eneste bolt på brua før den ble åpnet for trafikk.
De fleste butikkene stenger tidlig på søndager, så folk handler på lørdagen.
Han har jobbet som snekker i tretti år og liker fortsatt håndverket sitt.
Værmeldingen lover solskinn i morgen med en lett bris fra vest.
Hun lånte tre bøker på biblioteket og leste alle sammen på en uke.
Løk skal stekes sakte i smør til den blir myk og gyllen.
Kommunen planlegger å bygge en ny svømmehall ved siden av skolen.
Togene går hvert tiende minutt om dagen og hver halvtime om natta.
Bestemoren deres dyrket roser og tomater i den lille hagen bak huset.
Møtet varte i to timer, men ingen beslutning ble tatt til slutt.
Fiskerne dro ut før daggry mens sjøen lå rolig og stille.
Den nye telefonen koster altfor mye, så jeg beholder den gamle.
Snøen falt hele natta, og om morgenen lå veiene hvite og stille.
En god natts søvn betyr mer for helsa enn folk flest tror.
De malte gjerdet grønt og plantet blomster langs den smale stien.
Læreren ba elevene skrive en kort fortelling om sommeren.
Prisene steg igjen denne måneden, først og fremst fordi drivstoffet ble dyrere.
Konsertsalen var fullsatt, og publikum klappet i flere minutter.
Han rakk ikke den siste bussen og måtte gå hele veien hjem i regnet.
Skyll grønnsakene nøye og skjær dem i tynne skiver før servering.
Den gamle klokka på veggen har ikke virket siden i fjor vinter.
Turister kommer hit om sommeren for å gå i fjellet og bade i vannene.
Avisen skrev om en bonde som fant en sølvmynt på jordet sitt.
Hver vår kommer fuglene tilbake og bygger reir under taket.
Legen sa at han skulle hvile en uke og drikke rikelig med vann.
Naboene våre flyttet ut på landet fordi byen ble for bråkete.
Fabrikken sysselsetter to hundre mennesker og eksporterer maskiner til mange land.
Hun spiller fiolin om kveldene, og musikken driver ut over gårdsplassen.
Husk å låse døra og slukke lysene før du går.
Ferga ble innstilt på grunn av uværet, så vi ble der en natt til.
Talen hans var kort, men ærlig, og folk likte den svært godt.
En katt satt i vinduskarmen og så på regnet som rant nedover ruta.
Bakeriet åpner klokka seks, og lukten av brød fyller hele gata.
De har vært venner siden barndommen og møtes fremdeles hver fredag.
Museet tar vare på gammelt verktøy, fotografier og brev fra fiskeværene.
Vannet koker raskere med lokk på kjelen, og det sparer energi.
Dommeren stanset kampen to ganger fordi tåka ble for tett.
Informasjonen finnes på stasjonen, og billetter kjøpes i automaten.
Situasjonen i organisasjonen krevde en rask løsning fra styret.
""",
    "Nynorsk": """
Kjøkenet lukta nybaka brød då borna kom heim frå skulen.
Om hausten blir skogen raud og gyllen, og lufta er kald om natta.
Ein liten båt segla over bukta medan sola gjekk ned bak øyane.
Ingeniørane sjekka kvar einaste bolt på brua før ho vart opna for trafikk.
Dei fleste butikkane stengjer tidleg på søndagar, så folk handlar på laurdagen.
Han har arbeidd som snikkar i tretti år og likar framleis handverket sitt.
Vêrmeldinga lovar solskin i morgon med ein lett bris frå vest.
Ho lånte tre bøker på biblioteket og las alle saman på ei veke.
Lauk skal steikjast sakte i smør til han blir mjuk og gyllen.
Kommunen planlegg å byggje ein ny symjehall ved sida av skulen.
Toga går kvart tiande minutt om dagen og kvar halvtime om natta.
Bestemora deira dyrka roser og tomatar i den vesle hagen bak huset.
Møtet varte i to timar, men inga avgjerd vart teken til slutt.
Fiskarane drog ut før daggry medan sjøen låg roleg og stille.
Den nye telefonen kostar altfor mykje, så eg held på den gamle.
Snøen fall heile natta, og om morgonen låg vegane kvite og stille.
Ein god natts svevn tyder meir for helsa enn folk flest trur.
Dei måla gjerdet grønt og planta blomar langs den smale stigen.
Læraren bad elevane skrive ei kort forteljing om sommaren.
Prisane steig igjen denne månaden, først og fremst fordi drivstoffet vart dyrare.
Konsertsalen var fullsett, og publikum klappa i fleire minutt.
Han rakk ikkje den siste bussen og måtte gå heile vegen heim i regnet.
Skyl grønsakene nøye og skjer dei i tynne skiver før servering.
Den gamle klokka på veggen har ikkje verka sidan i fjor vinter.
Turistar kjem hit om sommaren for å gå i fjellet og bade i vatna.
Avisa skreiv om ein bonde som fann ein sølvmynt på jordet sitt.
Kvar vår kjem fuglane tilbake og byggjer reir under taket.
Legen sa at han skulle kvile ei veke og drikke rikeleg med vatn.
Naboane våre flytta ut på landet fordi byen vart for bråkete.
Fabrikken sysselset to hundre menneske og eksporterer maskinar til mange land.
Ho spelar fele om kveldane, og musikken driv ut over tunet.
Hugs å låse døra og sløkkje lysa før du går.
Ferja vart innstilt på grunn av uvêret, så vi vart verande ei natt til.
Talen hans var kort, men ærleg, og folk likte han svært godt.
Ein katt sat i glaskarmen og såg på regnet som rann nedover ruta.
Bakeriet opnar klokka seks, og lukta av brød fyller heile gata.
Dei har vore vener sidan barndomen og møtest framleis kvar fredag.
Museet tek vare på gamalt verktøy, fotografi og brev frå fiskeværa.
Vatnet kokar raskare med lok på kjelen, og det sparer energi.
Dommaren stansa kampen to gonger fordi skodda vart for tett.
Informasjonen finst på stasjonen, og billettar kan kjøpast i automaten.
Situasjonen i organisasjonen kravde ei rask løysing frå styret.
""",
}

# Second block: near-parallel everyday/administrative prose.  Parallel
# content across the candidate languages concentrates the learned differences
# on orthography and function words — exactly the evidence that separates the
# close pairs.
_TRAIN_TEXT_2 = {
    "English": """
After work she usually takes the tram home and makes dinner for the family.
The report shows that unemployment fell slightly during the last quarter.
If you want to apply for the position, you must send your application before Friday.
The road over the mountain is closed in winter because of snow and strong winds.
He bought a used car last year, and it has worked perfectly ever since.
The school arranges a trip to the capital for all pupils in the eighth grade.
We have to change trains twice before we reach the little town by the border.
The doctor examined the boy's knee and said that nothing was broken.
It is cheaper to travel in September, when the summer season is over.
The municipality has decided to renovate the swimming hall next year.
Many young people move to the big cities to study or to find work.
Could you please close the window? It is getting cold in here.
The book lay open on the table when the police entered the apartment.
They celebrated their fiftieth wedding anniversary with the whole family.
The bus stops right outside the hospital's main entrance every ten minutes.
In the evening the temperature drops quickly, so bring a warm sweater.
The insurance covers damage caused by fire, water and burglary.
He answered all the questions calmly and explained what had happened that night.
The bakery sells fresh rolls from early morning until late afternoon.
Several roads were flooded after the heavy rainfall on Tuesday.
""",
    "Danish": """
Efter arbejde tager hun som regel sporvognen hjem og laver aftensmad til familien.
Rapporten viser, at arbejdsløsheden faldt en smule i det seneste kvartal.
Hvis du vil søge stillingen, skal du sende din ansøgning inden fredag.
Vejen over fjeldet er lukket om vinteren på grund af sne og kraftig blæst.
Han købte en brugt bil sidste år, og den har kørt upåklageligt lige siden.
Skolen arrangerer en tur til hovedstaden for alle elever i ottende klasse.
Vi skal skifte tog to gange, før vi når den lille by ved grænsen.
Lægen undersøgte drengens knæ og sagde, at intet var brækket.
Det er billigere at rejse i september, når sommersæsonen er forbi.
Kommunen har besluttet at renovere svømmehallen til næste år.
Mange unge flytter til de store byer for at studere eller finde arbejde.
Vil du ikke lukke vinduet? Det begynder at blive koldt herinde.
Bogen lå opslået på bordet, da politiet trådte ind i lejligheden.
De fejrede deres guldbryllup sammen med hele familien.
Bussen stopper lige uden for hospitalets hovedindgang hvert tiende minut.
Om aftenen falder temperaturen hurtigt, så tag en varm trøje med.
Forsikringen dækker skader forårsaget af brand, vand og indbrud.
Han besvarede alle spørgsmålene roligt og forklarede, hvad der var sket den nat.
Bageren sælger friske rundstykker fra tidlig morgen til sen eftermiddag.
Flere veje blev oversvømmet efter det kraftige regnvejr tirsdag.
""",
    "Swedish": """
Efter jobbet tar hon oftast spårvagnen hem och lagar middag åt familjen.
Rapporten visar att arbetslösheten sjönk något under det senaste kvartalet.
Om du vill söka tjänsten måste du skicka in din ansökan före fredag.
Vägen över fjället är stängd på vintern på grund av snö och hårda vindar.
Han köpte en begagnad bil i fjol, och den har fungerat felfritt sedan dess.
Skolan ordnar en resa till huvudstaden för alla elever i åttonde klass.
Vi måste byta tåg två gånger innan vi når den lilla staden vid gränsen.
Läkaren undersökte pojkens knä och sade att ingenting var brutet.
Det är billigare att resa i september när sommarsäsongen är över.
Kommunen har beslutat att renovera simhallen nästa år.
Många unga flyttar till storstäderna för att plugga eller hitta jobb.
Kan du vara snäll och stänga fönstret? Det börjar bli kallt här inne.
Boken låg uppslagen på bordet när polisen steg in i lägenheten.
De firade sin guldbröllopsdag tillsammans med hela familjen.
Bussen stannar precis utanför sjukhusets huvudentré var tionde minut.
På kvällen sjunker temperaturen snabbt, så ta med en varm tröja.
Försäkringen täcker skador orsakade av brand, vatten och inbrott.
Han besvarade alla frågor lugnt och förklarade vad som hade hänt den natten.
Bageriet säljer färska frallor från tidig morgon till sen eftermiddag.
Flera vägar översvämmades efter det kraftiga regnet i tisdags.
""",
    "Bokmal": """
Etter jobb tar hun som regel trikken hjem og lager middag til familien.
Rapporten viser at arbeidsledigheten sank noe i det siste kvartalet.
Hvis du vil søke på stillingen, må du sende søknaden din innen fredag.
Veien over fjellet er stengt om vinteren på grunn av snø og sterk vind.
Han kjøpte en bruktbil i fjor, og den har virket helt fint siden.
Skolen arrangerer en tur til hovedstaden for alle elevene på åttende trinn.
Vi må bytte tog to ganger før vi når den lille byen ved grensen.
Legen undersøkte kneet til gutten og sa at ingenting var brukket.
Det er billigere å reise i september, når sommersesongen er over.
Kommunen har bestemt seg for å pusse opp svømmehallen neste år.
Mange unge flytter til de store byene for å studere eller finne seg jobb.
Kan du være så snill å lukke vinduet? Det begynner å bli kaldt her inne.
Boka lå oppslått på bordet da politiet kom inn i leiligheten.
De feiret gullbryllupet sitt sammen med hele familien.
Bussen stopper rett utenfor hovedinngangen til sykehuset hvert tiende minutt.
Om kvelden synker temperaturen raskt, så ta med deg en varm genser.
Forsikringen dekker skader forårsaket av brann, vann og innbrudd.
Han svarte rolig på alle spørsmålene og forklarte hva som hadde skjedd den natten.
Bakeren selger ferske rundstykker fra tidlig morgen til sein ettermiddag.
Flere veier ble oversvømt etter det kraftige regnværet tirsdag.
""",
    "Nynorsk": """
Etter arbeid tek ho som regel trikken heim og lagar middag til familien.
Rapporten viser at arbeidsløysa sokk noko i det siste kvartalet.
Dersom du vil søkje på stillinga, må du sende søknaden din innan fredag.
Vegen over fjellet er stengd om vinteren på grunn av snø og sterk vind.
Han kjøpte ein bruktbil i fjor, og han har verka heilt fint sidan.
Skulen arrangerer ein tur til hovudstaden for alle elevane på åttande steget.
Vi må byte tog to gonger før vi når den vesle byen ved grensa.
Legen undersøkte kneet til guten og sa at ingenting var brote.
Det er billegare å reise i september, når sommarsesongen er over.
Kommunen har bestemt seg for å pusse opp symjehallen neste år.
Mange unge flyttar til dei store byane for å studere eller finne seg arbeid.
Kan du vere så snill å late att vindauget? Det byrjar å bli kaldt her inne.
Boka låg oppslått på bordet då politiet kom inn i leilegheita.
Dei feira gullbryllaupet sitt saman med heile familien.
Bussen stoppar rett utanfor hovudinngangen til sjukehuset kvart tiande minutt.
Om kvelden søkk temperaturen raskt, så ta med deg ein varm genser.
Forsikringa dekkjer skadar som kjem av brann, vatn og innbrot.
Han svara roleg på alle spørsmåla og forklarte kva som hadde hendt den natta.
Bakaren sel ferske rundstykke frå tidleg morgon til sein ettermiddag.
Fleire vegar vart oversvømde etter det kraftige regnvêret tysdag.
""",
}

# Round-4 expansion: news/administrative register (the register the labeled
# corpus leans on) with the orthography that separates the close pairs laid
# on thick — Danish ud-/ej/øj/af/-tion/soft-d/-ede vs Bokmål ut-/ei/øy/av/
# -sjon/-et vs Nynorsk ikkje/kva/vere/-inga, Swedish och/ä/ö.
_TRAIN_TEXT_3 = {
    "English": """
The city council approved new bicycle lanes along the main road into the harbour district.
Parents have complained about the long waiting lists for kindergarten places.
Negotiations about next year's fishing quotas begin in Brussels on Monday.
Residents can comment on the planned wind farm at a public hearing in March.
The handball team won its third straight match and now leads the league.
The fire service warns of high risk of forest fires after the dry summer.
The vaccination campaign starts in October and targets everyone over sixty-five.
Bus drivers accepted the wage offer after two days of negotiations.
From January all citizens must use the new digital mailbox for official letters.
The school board wants to offer free lunch to all pupils from next autumn.
The toll on the old bridge rises by two kroner at the turn of the year.
Turnout in the local elections was the highest in twenty years.
The housing association meets on Wednesday to decide on the roof renovation.
The municipality opens two new recycling stations on the edge of town.
Archaeologists found the remains of a medieval trading post under the square.
The theatre opens its season with a play about a lighthouse keeper's family.
The chess club arranges an open tournament in the community hall this weekend.
Heavy snowfall closed the mountain pass for several hours on Wednesday morning.
The dentist recommends that children brush their teeth twice a day.
Sales of electric cars rose sharply in the second half of the year.
The old swimming hall will be torn down when the new one is ready.
A leaking water pipe flooded the cellar of the town hall during the night.
The choir rehearses every Tuesday evening in the chapel by the school.
Customs officers seized a large shipment of counterfeit goods at the border.
The weather service expects mild days and night frost during the week.
The union fears that the closure of the sawmill will cost eighty jobs.
The course teaches older people how to pay bills safely online.
The apartment needs new wiring before the family can move in.
Researchers are mapping how the fjord's cod stock has changed over forty years.
The airline opens a direct route between the two capitals in April.
""",
    "Danish": """
Byrådet godkendte nye cykelstier langs hovedvejen ud mod havnekvarteret.
Forældre har klaget over de lange ventelister til en plads i børnehaven.
Forhandlingerne om næste års fiskekvoter begynder i Bruxelles på mandag.
Borgerne kan kommentere den planlagte vindmøllepark ved et offentligt møde i marts.
Håndboldholdet vandt sin tredje kamp i træk og fører nu rækken.
Beredskabet advarer om høj risiko for skovbrande efter den tørre sommer.
Vaccinationskampagnen begynder i oktober og retter sig mod alle over femogtres.
Buschaufførerne sagde ja til løntilbuddet efter to dages forhandlinger.
Fra januar skal alle borgere bruge den nye digitale postkasse til breve fra det offentlige.
Skolebestyrelsen vil tilbyde gratis frokost til alle elever fra næste efterår.
Afgiften på den gamle bro stiger med to kroner ved årsskiftet.
Valgdeltagelsen ved kommunalvalget var den højeste i tyve år.
Andelsboligforeningen mødes onsdag for at beslutte sig om udskiftningen af taget.
Kommunen åbner to nye genbrugsstationer i udkanten af byen.
Arkæologer fandt resterne af en middelalderlig handelsplads under torvet.
Teatret åbner sæsonen med et stykke om en fyrpassers familie.
Skakklubben afholder en åben turnering i forsamlingshuset i weekenden.
Kraftigt snefald lukkede bjergpasset i flere timer onsdag morgen.
Tandlægen anbefaler, at børn børster tænder to gange om dagen.
Salget af elbiler steg kraftigt i andet halvår.
Den gamle svømmehal rives ned, når den nye står færdig.
Et utæt vandrør satte rådhusets kælder under vand i løbet af natten.
Koret øver hver tirsdag aften i kapellet ved skolen.
Tolderne beslaglagde et stort parti forfalskede varer ved grænsen.
Vejrtjenesten venter milde dage og nattefrost i ugens løb.
Fagforeningen frygter, at lukningen af savværket vil koste firs arbejdspladser.
Kurset lærer ældre at betale regninger sikkert på nettet.
Lejligheden skal have nye elinstallationer, før familien kan flytte ind.
Forskere kortlægger, hvordan fjordens torskebestand har ændret sig gennem fyrre år.
Flyselskabet åbner en direkte rute mellem de to hovedstæder i april.
Rejsen med færgen tager halvanden time, hvis vejret ellers arter sig.
Udviklingen på boligmarkedet har overrasket de fleste økonomer i år.
Han øjnede en mulighed for at sælge forretningen, inden afgiften blev sat op.
Arbejdet med motorvejen er udskudt til efter sommerferien.
Uden flere penge fra staten må svømmehallen holde lukket hele vinteren.
""",
    "Swedish": """
Kommunfullmäktige godkände nya cykelbanor längs huvudvägen ut mot hamnkvarteren.
Föräldrar har klagat över de långa väntelistorna till en plats på förskolan.
Förhandlingarna om nästa års fiskekvoter inleds i Bryssel på måndag.
Invånarna kan lämna synpunkter på den planerade vindkraftsparken vid ett samråd i mars.
Handbollslaget vann sin tredje raka match och leder nu serien.
Räddningstjänsten varnar för hög risk för skogsbränder efter den torra sommaren.
Vaccinationskampanjen inleds i oktober och riktar sig till alla över sextiofem.
Busschaufförerna sade ja till lönebudet efter två dagars förhandlingar.
Från januari måste alla medborgare använda den nya digitala brevlådan för myndighetspost.
Skolstyrelsen vill erbjuda gratis lunch till alla elever från och med nästa höst.
Avgiften på den gamla bron höjs med två kronor vid årsskiftet.
Valdeltagandet i kommunalvalet var det högsta på tjugo år.
Bostadsrättsföreningen träffas på onsdag för att besluta om takrenoveringen.
Kommunen öppnar två nya återvinningsstationer i utkanten av staden.
Arkeologer hittade resterna av en medeltida handelsplats under torget.
Teatern öppnar säsongen med en pjäs om en fyrvaktares familj.
Schackklubben ordnar en öppen turnering i bygdegården i helgen.
Kraftigt snöfall stängde fjällpasset i flera timmar på onsdagsmorgonen.
Tandläkaren rekommenderar att barn borstar tänderna två gånger om dagen.
Försäljningen av elbilar ökade kraftigt under andra halvåret.
Den gamla simhallen rivs när den nya står klar.
En läckande vattenledning satte stadshusets källare under vatten under natten.
Kören övar varje tisdagskväll i kapellet vid skolan.
Tulltjänstemännen beslagtog ett stort parti förfalskade varor vid gränsen.
Vädertjänsten väntar milda dagar och nattfrost under veckan.
Facket befarar att nedläggningen av sågverket kostar åttio jobb.
Kursen lär äldre att betala räkningar säkert på nätet.
Lägenheten behöver nya elinstallationer innan familjen kan flytta in.
Forskare kartlägger hur fjordens torskbestånd har förändrats under fyrtio år.
Flygbolaget öppnar en direktlinje mellan de två huvudstäderna i april.
""",
    "Nynorsk": """
Kommunestyret godkjende nye sykkelvegar langs hovudvegen ut mot hamnekvartala.
Foreldre har klaga på dei lange ventelistene for å få plass i barnehagen.
Forhandlingane om fiskekvotane for neste år tek til i Brussel måndag.
Innbyggjarane kan seie meininga si om den planlagde vindparken på eit ope møte i mars.
Handballaget vann sin tredje kamp på rad og leier no serien.
Brannvesenet åtvarar mot høg fare for skogbrann etter den tørre sommaren.
Vaksinasjonskampanjen tek til i oktober og rettar seg mot alle over sekstifem.
Bussjåførane sa ja til lønstilbodet etter to dagar med forhandlingar.
Frå januar må alle innbyggjarar bruke den nye digitale postkassa til brev frå det offentlege.
Skulestyret vil tilby gratis lunsj til alle elevane frå neste haust.
Avgifta på den gamle brua aukar med to kroner ved årsskiftet.
Valdeltakinga ved kommunevalet var den høgaste på tjue år.
Burettslaget møtest onsdag for å avgjere om taket skal skiftast ut.
Kommunen opnar to nye gjenvinningsstasjonar i utkanten av byen.
Arkeologar fann restane av ein mellomaldersk handelsstad under torget.
Teateret opnar sesongen med eit stykke om familien til ein fyrvaktar.
Sjakklubben skipar til ei open turnering i grendehuset i helga.
Kraftig snøfall stengde fjellovergangen i fleire timar onsdag morgon.
Tannlegen rår til at born pussar tennene to gonger om dagen.
Salet av elbilar auka kraftig i andre halvår.
Den gamle symjehallen vert riven når den nye står klar.
Eit lekk vassrøyr sette kjellaren i rådhuset under vatn i løpet av natta.
Koret øver kvar tysdagskveld i kapellet ved skulen.
Tollarane beslagla eit stort parti forfalska varer ved grensa.
Vêrtenesta ventar milde dagar og nattefrost utover veka.
Fagforeininga fryktar at nedlegginga av sagbruket vil koste åtti arbeidsplassar.
Kurset lærer eldre korleis dei betaler rekningar trygt på nettet.
Leilegheita treng nytt elektrisk anlegg før familien kan flytte inn.
Forskarar kartlegg korleis torskebestanden i fjorden har endra seg gjennom førti år.
Flyselskapet opnar ei direkte rute mellom dei to hovudstadene i april.
""",
    "Bokmal": """
Kommunestyret godkjente nye sykkelveier langs hovedveien ut mot havnekvartalene.
Foreldre har klaget på de lange ventelistene for å få plass i barnehagen.
Forhandlingene om neste års fiskekvoter begynner i Brussel mandag.
Innbyggerne kan si sin mening om den planlagte vindparken på et åpent møte i mars.
Håndballaget vant sin tredje kamp på rad og leder nå serien.
Brannvesenet advarer mot høy fare for skogbrann etter den tørre sommeren.
Vaksinasjonskampanjen begynner i oktober og retter seg mot alle over sekstifem.
Bussjåførene sa ja til lønnstilbudet etter to dager med forhandlinger.
Fra januar må alle innbyggere bruke den nye digitale postkassen til brev fra det offentlige.
Skolestyret vil tilby gratis lunsj til alle elevene fra neste høst.
Avgiften på den gamle brua øker med to kroner ved årsskiftet.
Valgdeltakelsen ved kommunevalget var den høyeste på tjue år.
Borettslaget møtes onsdag for å avgjøre om taket skal skiftes ut.
Kommunen åpner to nye gjenvinningsstasjoner i utkanten av byen.
Arkeologer fant restene av en middelaldersk handelsplass under torget.
Teateret åpner sesongen med et stykke om familien til en fyrvokter.
Sjakklubben arrangerer en åpen turnering i grendehuset i helgen.
Kraftig snøfall stengte fjellovergangen i flere timer onsdag morgen.
Tannlegen anbefaler at barn pusser tennene to ganger om dagen.
Salget av elbiler økte kraftig i andre halvår.
Den gamle svømmehallen rives når den nye står klar.
Et lekk vannrør satte kjelleren i rådhuset under vann i løpet av natten.
Koret øver hver tirsdagskveld i kapellet ved skolen.
Tollerne beslagla et stort parti forfalskede varer ved grensen.
Værtjenesten venter milde dager og nattefrost utover uken.
Fagforeningen frykter at nedleggelsen av sagbruket vil koste åtti arbeidsplasser.
Kurset lærer eldre hvordan de betaler regninger trygt på nettet.
Leiligheten trenger nytt elektrisk anlegg før familien kan flytte inn.
Forskere kartlegger hvordan torskebestanden i fjorden har endret seg gjennom førti år.
Flyselskapet åpner en direkte rute mellom de to hovedstedene i april.
Reisen med ferga tar halvannen time hvis været ellers oppfører seg.
Utviklingen på boligmarkedet har overrasket de fleste økonomene i år.
Han øynet en mulighet til å selge forretningen før avgiften ble satt opp.
Arbeidet med motorveien er utsatt til etter sommerferien.
Uten mer penger fra staten må svømmehallen holde stengt hele vinteren.
""",
}

TRAIN_TEXT = {
    lang: _TRAIN_TEXT_1[lang] + _TRAIN_TEXT_2[lang] + _TRAIN_TEXT_3[lang]
    for lang in _TRAIN_TEXT_1
}


# Curated common-vocabulary lexicon (flat weight, not Zipf-ranked): frequent
# content-word FORMS whose orthography separates the close pairs — Danish
# ud-/-hed/-tion/skov/fik vs Bokmål ut-/-het/-sjon/skog/fikk vs Nynorsk
# -inga/kva/ikkje/vart, Swedish -ning/och/ä.  Provenance: general newspaper
# vocabulary plus contrast forms added in rounds 4-5 while iterating against
# the development corpus's confusions (tests/data/langid_corpus.tsv) — that
# corpus is therefore IN-SAMPLE for this lexicon; the out-of-sample estimate
# comes from the one-shot holdout set (tests/data/langid_holdout.tsv),
# authored after the lexicon was frozen and scored exactly once
# (tests/test_langid_agreement.py).
EXTRA_WORDS = {
    "Danish": """af ud op ind ned hen hvad hvor hvordan hvorfor hvornår ikke efter sidste først
mellem gennem igennem uden inden indenfor udenfor omkring måske allerede altid aldrig
arbejde arbejdet arbejder arbejdede udvikling udviklingen udstilling udstillingen uddannelse uddannelsen
undersøgelse undersøgelsen oplysning oplysninger mulighed muligheden muligheder sundhed sundheden
sygdom sygdommen sygehus sygehuset lejlighed lejligheden samfund samfundet videnskab videnskaben
århundrede århundredet tyve tredive fyrre halvtreds tres halvfjerds firs halvfems
fik fået får gik gået går stod stået står så set ser blev blevet bliver
opdaget opdagede oplevede oplevet fortalte fortalt talte talt solgte solgt købte købt
skov skoven skove vej vejen veje nej sejr øje øjne høj højere højest
gade gaden uge ugen måned måneden tid tiden sted steder by byen
regering regeringen miljø miljøet kærlighed samarbejde virksomhed virksomheder myndighed myndigheder
spørgsmål svar løsning løsninger forskning forskningen udgift udgifter indtægt indtægter
næste stor store større størst lille små mindre mindst god bedre bedst
dreng pige mand kvinde barn børn menneske mennesker ven venner
sundhedsvæsen sundhedsvæsenet hovedstaden udlandet indbygger indbyggere
anmeldelse anmeldelser biograf biografen biograferne avis avisen aviser
afprøver afprøvede hjælpe hjælp hjælpen køen skolerne bylinjerne
regnskovene frøart borgmester borgmesteren bekymrede foråret
bedstefar bedstefaren bedstemor hendes hende tilladelse tilladelsen havnen
imponerende præcision spillede strikkede fødselsdag hejste stormvarslet
middagstid dyrkede ryddede mågerne kredsede krydser billetpriserne""",
    "Bokmal": """av ut opp inn ned bort hva hvor hvordan hvorfor når ikke etter siste først
mellom gjennom uten innen innenfor utenfor omkring kanskje allerede alltid aldri
arbeid arbeidet arbeider utvikling utviklingen utstilling utstillingen utdanning utdanningen
undersøkelse undersøkelsen opplysning opplysninger mulighet muligheten muligheter helse helsen
sykdom sykdommen sykehus sykehuset leilighet leiligheten samfunn samfunnet vitenskap vitenskapen
århundre århundret tjue tretti førti femti seksti sytti åtti nitti
fikk fått får gikk gått går sto stått står så sett ser ble blitt blir
oppdaget opplevde opplevd fortalte fortalt snakket solgte solgt kjøpte kjøpt
skog skogen skoger vei veien veier nei seier øye øyne høy høyere høyest
gate gaten uke uken måned måneden tid tiden sted steder by byen
regjering regjeringen miljø miljøet kjærlighet samarbeid virksomhet virksomheter myndighet myndigheter
spørsmål svar løsning løsninger forskning forskningen utgift utgifter inntekt inntekter
neste stor store større størst liten små mindre minst god bedre best
gutt jente mann kvinne barn mennesker venn venner
helsevesen helsevesenet hovedstaden utlandet innbygger innbyggere
anmeldelse anmeldelser kino kinoen avis avisen aviser
ordfører ordføreren lovet kollektivtransport våren bøndene bekymret
bestefar bestefaren bestemor bestemoren hennes henne
prøveprosjekt tillatelse tillatelsen havna dyrket vika
fylke fylket fylkeskommunen nabolaget framtiden fremtiden
imponerende ryddet handlet måkene kretset krysser billettprisene
turstien kanelboller prisene""",
    "Nynorsk": """av ut opp inn ned bort kva kvar korleis kvifor når ikkje etter siste først
mellom gjennom utan innan innanfor utanfor omkring kanskje allereie alltid aldri
arbeid arbeidet arbeider utvikling utviklinga utstilling utstillinga utdanning utdanninga
undersøking undersøkinga opplysning opplysningar moglegheit høve helse helsa
sjukdom sjukdommen sjukehus sjukehuset leilegheit leilegheita samfunn samfunnet vitskap vitskapen
hundreår hundreåret tjue tretti førti femti seksti sytti åtti nitti
fekk fått får gjekk gått går sto stått står såg sett ser vart blitt blir vert
oppdaga opplevde opplevd fortalde fortalt snakka selde selt kjøpte kjøpt
skog skogen skogar veg vegen vegar nei siger auge augo høg høgare høgast
gate gata veke veka månad månaden tid tida stad stader by byen
regjering regjeringa miljø miljøet kjærleik samarbeid verksemd verksemder styresmakt styresmakter
spørsmål svar løysing løysingar forsking forskinga utgift utgifter inntekt inntekter
neste stor store større størst liten små mindre minst god betre best
gut jente mann kvinne barn born menneske menneska venn venner
helsevesen helsevesenet hovudstaden utlandet innbyggjar innbyggjarar
melding meldingar kino kinoen avis avisa aviser
ordførar ordføraren lova uroa manglande rimelege bustad bustader
fleire imponerande presisjonen framført hennar honom
fylkeskommunen framtida kvelden løyve løyvet hamna
trass dyrka vika prøveprosjektet tusenvis
no att då gav dottera sonen straum rydda letta kutta dekte
høyringa frontruta tolvtida ete drog""",
    "Swedish": """av ut upp in ner bort vad var hur varför när inte efter sista först
mellan genom utan inom innanför utanför omkring kanske redan alltid aldrig
arbete arbetet arbetar utveckling utvecklingen utställning utställningen utbildning utbildningen
undersökning undersökningen upplysning upplysningar möjlighet möjligheten möjligheter hälsa hälsan
sjukdom sjukdomen sjukhus sjukhuset lägenhet lägenheten samhälle samhället vetenskap vetenskapen
århundrade århundradet tjugo trettio fyrtio femtio sextio sjuttio åttio nittio
fick fått får gick gått går stod stått står såg sett ser blev blivit blir
upptäckte upptäckt upplevde upplevt berättade berättat pratade sålde sålt köpte köpt
skog skogen skogar väg vägen vägar nej seger öga ögon hög högre högst
gata gatan vecka veckan månad månaden tid tiden plats platser stad staden
regering regeringen miljö miljön kärlek samarbete verksamhet verksamheter myndighet myndigheter
fråga frågor svar lösning lösningar forskning forskningen utgift utgifter inkomst inkomster
nästa stor stora större störst liten små mindre minst god bättre bäst
pojke flicka man kvinna barn människa människor vän vänner
sjukvård sjukvården huvudstaden utlandet invånare
recension recensioner bio bion biograf tidning tidningen tidningar
testar hjälpa hjälp hjälpen smärta kronisk kroniska forskare
borgmästare borgmästaren oroliga våren nederbörd nederbörden bönderna
farfar morfar hennes henne tillstånd tillståndet hamnen äntligen""",
    "English": """of out up in down away what where how why when not after last first
between through without inside outside around maybe already always never
work worked working development exhibition education examination
investigation information possibility opportunity health healthcare
sickness illness hospital apartment society science
century twenty thirty forty fifty sixty seventy eighty ninety
got gotten gets went gone goes stood stands saw seen sees became become becomes
discovered experienced told talked sold bought
forest forests road roads no victory eye eyes high higher highest
street week month time place city town
government environment love cooperation business authority authorities
question answer solution research expense income
next big bigger biggest little small smaller smallest good better best
boy girl man woman child children person people friend friends
capital abroad inhabitant inhabitants
review reviews cinema newspaper newspapers""",
}
