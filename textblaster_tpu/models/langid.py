"""Statistical language identification model.

The reference embeds the ``lingua`` detector built over a hardcoded candidate
set {English, Danish, Swedish, Nynorsk, Bokmal} on every call
(``/root/reference/src/pipeline/filters/language_filter.rs:39-46``).  lingua's
proprietary n-gram tables cannot be shipped here, so this module provides the
framework's own statistical model with the same *interface* and candidate set:
a hashed character-trigram naive-Bayes classifier whose profiles are trained
from two built-in sources — frequency-ranked function-word lists
(Zipf-weighted) and per-language running prose
(:mod:`textblaster_tpu.models.langid_data`).  Decision agreement is measured
on a labeled out-of-sample corpus in ``tests/test_langid_agreement.py``.

The model is deliberately table-shaped for TPU execution: scoring is
``logprob_table[hash(trigram)] -> [n_langs]`` gathers summed per document —
on device this is a gather + segmented sum over the packed byte tensor (see
:mod:`textblaster_tpu.ops.langid_tpu`), on host the identical numpy
computation, so host and device decisions agree exactly.

Confidence semantics follow lingua's relative-confidence shape: softmax over
per-language total log-likelihoods, sharpening with document length.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "LANGUAGES",
    "ISO_TO_NAME",
    "NAME_TO_ISO",
    "LangIdModel",
    "get_model",
]

# Candidate set and display names exactly as lingua's Display renders them
# (language_filter.rs:39-46, metadata asserted in language_filter.rs:203-218).
LANGUAGES: Tuple[str, ...] = ("English", "Danish", "Swedish", "Nynorsk", "Bokmal")
ISO_TO_NAME: Dict[str, str] = {
    "eng": "English",
    "dan": "Danish",
    "swe": "Swedish",
    "nno": "Nynorsk",
    "nob": "Bokmal",
}
NAME_TO_ISO: Dict[str, str] = {v: k for k, v in ISO_TO_NAME.items()}

TABLE_BITS = 16
TABLE_SIZE = 1 << TABLE_BITS

# Frequency-ranked word lists (approximate top-of-corpus orderings).  Rank r
# contributes Zipf weight 1/(r+1).  These are public-knowledge function-word
# inventories, not copied from any single source.
_WORDS: Dict[str, Sequence[str]] = {
    "English": (
        "the of and a to in is you that it he was for on are as with his they i".split()
        + "at be this have from or one had by word but not what all were we when".split()
        + "your can said there use an each which she do how their if will up other".split()
        + "about out many then them these so some her would make like him into time".split()
        + "has look two more write go see number no way could people my than first".split()
        + "water been call who oil its now find long down day did get come made may".split()
        + "part over new sound take only little work know place year live me back".split()
        + "give most very after thing our just name good sentence man think say great".split()
        + "where help through much before line right too mean old any same tell boy".split()
        + "follow came want show also around form three small set put end does".split()
    ),
    "Danish": (
        "og i at det er en den til af som på de med han der ikke et var jeg".split()
        + "men sig har om vi hun havde fra ham du kan nu over så skal ved kunne".split()
        + "eller hvad deres efter op under være dem også min alle noget meget her".split()
        + "hele andre blev hvor da sin mod selv ud se os kom mig når hvis hans".split()
        + "hende få vil end år mellem sige to både sådan dag gang denne siger".split()
        + "uden gennem lidt mand skulle vide tid tilbage først godt mere bliver".split()
        + "frem endnu går ind fordi ligger derfor siden får netop blandt mange".split()
        + "kærlighed hjælp måde allerede ingen intet tre fik stadig lige jo nej".split()
        + "altid bare måske kroner arbejde hvordan verden børn gerne danske dansk".split()
        + "københavn øjne hjem huset aldrig næsten igen store mindre penge".split()
        + "vej vejret nej sejr lejlighed øje høj hedder gade uge sprog måned".split()
        + "sætning svært lærer tænke længe færdig træffe hjælpe søndag onsdag".split()
    ),
    "Swedish": (
        "och i att det som en på är av för med den till han var inte om de ett".split()
        + "men sig jag hade vi hon så från vid kan nu över skall ska kunde eller".split()
        + "vad deras efter upp under vara dem också min alla något mycket här hela".split()
        + "andra blev där då sin mot själv ut se oss kom mig när om hans henne få".split()
        + "vill än år mellan säga två både sådan dag gång denna säger utan genom".split()
        + "lite man skulle veta tid tillbaka först bra mer blir fram ännu går in".split()
        + "eftersom ligger därför sedan får just bland många kärlek hjälp sätt".split()
        + "redan ingen inget tre fick fortfarande precis ju nej alltid bara kanske".split()
        + "kronor arbete hur världen barn gärna svenska svensk stockholm ögon hem".split()
        + "huset aldrig nästan igen stora mindre pengar något människor".split()
    ),
    "Nynorsk": (
        "og i å det er ein den til av som på dei med han der ikkje eit var eg".split()
        + "men seg har om vi ho hadde frå han du kan no over så skal ved kunne".split()
        + "eller kva deira etter opp under vere dei også min alle noko mykje her".split()
        + "heile andre vart kvar då sin mot sjølv ut sjå oss kom meg når viss hans".split()
        + "henne få vil enn år mellom seie to både slik dag gong denne seier utan".split()
        + "gjennom litt mann skulle vite tid tilbake først godt meir blir fram".split()
        + "enno går inn fordi ligg difor sidan får nettopp blant mange kjærleik".split()
        + "hjelp måte allereie ingen ingenting tre fekk framleis nett jo nei".split()
        + "alltid berre kanskje kroner arbeid korleis verda born gjerne norske".split()
        + "norsk oslo auge heim huset aldri nesten igjen store mindre pengar".split()
    ),
    "Bokmal": (
        "og i å det er en den til av som på de med han der ikke et var jeg".split()
        + "men seg har om vi hun hadde fra ham du kan nå over så skal ved kunne".split()
        + "eller hva deres etter opp under være dem også min alle noe mye her".split()
        + "hele andre ble hvor da sin mot selv ut se oss kom meg når hvis hans".split()
        + "henne få vil enn år mellom si to både slik dag gang denne sier uten".split()
        + "gjennom litt mann skulle vite tid tilbake først godt mer blir fram".split()
        + "ennå går inn fordi ligger derfor siden får nettopp blant mange".split()
        + "kjærlighet hjelp måte allerede ingen ingenting tre fikk fortsatt".split()
        + "akkurat jo nei alltid bare kanskje kroner arbeid hvordan verden barn".split()
        + "gjerne norske norsk oslo øyne hjem huset aldri nesten igjen store".split()
        + "vei været nei seier leilighet øye høy heter gate uke språk måned".split()
        + "setning vanskelig lærer tenke lenge ferdig treffe hjelpe søndag onsdag".split()
    ),
}


def _hash3(c1: int, c2: int, c3: int) -> int:
    """Deterministic trigram hash; identical formulation on host and device."""
    return (c1 * 961 + c2 * 31 + c3) & (TABLE_SIZE - 1)


def _hash3_vec(arr: "np.ndarray") -> "np.ndarray":
    """Vectorized :func:`_hash3` over a codepoint sequence ``[n] -> [n-2]``.
    Training and scoring must hash identically or the table silently
    mistrains.  The device kernel carries its own jnp twin of this formula
    (:mod:`textblaster_tpu.ops.langid_tpu`, ``langid_scores``) — change all
    three together, and the host/device parity suite will catch a miss."""
    return (arr[:-2] * 961 + arr[1:-1] * 31 + arr[2:]) & (TABLE_SIZE - 1)


# 31^-1 mod 2^32 — 31 is odd, hence invertible; lets the per-word rolling
# hash be computed from two prefix arrays instead of a Python loop.
_INV31 = np.uint32(pow(31, -1, 1 << 32))


def _word_hash_vec(arr: "np.ndarray") -> "np.ndarray":
    """Rolling hash ``h = h*31 + c`` of every boundary-delimited word in a
    normalized codepoint sequence (0 = boundary), masked to the table.

    Vectorized via modular inverses: with ``T_i = sum_{j<=i} c_j * 31^-j``
    (mod 2^32), the hash of span ``[a, b]`` is ``31^b * (T_b - T_{a-1})`` —
    exactly the loop's value, since 31 is invertible mod 2^32.  The device
    kernel computes the identical value with a segmented affine scan
    (:mod:`textblaster_tpu.ops.langid_tpu`)."""
    c = arr.astype(np.uint32)
    n = c.shape[0]
    if n == 0:
        return np.empty(0, dtype=np.int64)
    pow31 = np.ones(n, dtype=np.uint32)
    inv31 = np.ones(n, dtype=np.uint32)
    if n > 1:
        pow31[1:] = 31
        inv31[1:] = _INV31
        # NumPy promotes the cumprod accumulator to uint64; the final
        # uint32 cast truncates back to the intended mod-2^32 values.
        pow31 = np.cumprod(pow31).astype(np.uint32)
        inv31 = np.cumprod(inv31).astype(np.uint32)
    t = np.cumsum(c * inv31, dtype=np.uint32)
    is_b = arr == 0
    # Word spans [a, b]: a follows a boundary (or starts the array), b
    # precedes one (or ends it).  _normalize_codepoints wraps the stream in
    # boundaries, but stay robust to bare sequences.
    starts = np.flatnonzero(~is_b & np.concatenate(([True], is_b[:-1])))
    ends = np.flatnonzero(~is_b & np.concatenate((is_b[1:], [True])))
    if starts.size == 0:
        return np.empty(0, dtype=np.int64)
    t_prev = np.where(starts > 0, t[np.maximum(starts - 1, 0)], np.uint32(0))
    h = pow31[ends] * (t[ends] - t_prev)
    return (h & np.uint32(TABLE_SIZE - 1)).astype(np.int64)


def _normalize_codepoints(text: str) -> List[int]:
    """Lowercase letters kept; every other char becomes the boundary marker.

    Runs of boundary markers collapse, and the sequence is wrapped in
    boundaries, so word-edge trigrams are well-defined.  Training-time form
    (per-char Python); the hot scoring path uses the vectorized twin below.
    """
    out: List[int] = [0]
    for ch in text.lower():
        if ch.isalpha():
            out.append(ord(ch))
        elif out[-1] != 0:
            out.append(0)
    if out[-1] != 0:
        out.append(0)
    return out


def _norm_tables():
    """(lower [MAX_CP] int32, alpha-of-lower [MAX_CP] bool) — the same
    char/lower tables the device kernel gathers (ops/device.py), so the host
    scorer and the device kernel normalize identically by construction
    (including chars whose str.lower() is multi-char, which both treat as
    identity — unlike whole-string ``text.lower()``)."""
    global _NORM_TABLES
    if _NORM_TABLES is None:
        from ..ops.device import _class_table_np, _lower_table_np
        from ..utils import chartables as ct

        lower = _lower_table_np()
        alpha = (_class_table_np()[lower] & ct.ALPHA) != 0
        _NORM_TABLES = (lower, alpha)
    return _NORM_TABLES


_NORM_TABLES = None


def _normalize_vec(text: str) -> "np.ndarray":
    """Vectorized scoring-path twin of :func:`_normalize_codepoints`:
    boundary-wrapped lowercased letters with non-letter runs collapsed,
    as an int64 array."""
    from ..utils.chartables import codepoints

    lower, alpha = _norm_tables()
    arr = codepoints(text).astype(np.int64)
    clipped = np.minimum(arr, lower.shape[0] - 1)
    # Out-of-table codepoints are non-letters; `low` is only read at letter
    # positions, so the clipped gather is enough.
    low = lower[clipped]
    is_letter = np.zeros(arr.shape[0] + 2, dtype=bool)
    is_letter[1:-1] = alpha[clipped] & (arr < lower.shape[0])
    vals = np.zeros(arr.shape[0] + 2, dtype=np.int64)
    vals[1:-1] = np.where(is_letter[1:-1], low, 0)
    # Keep letters, plus the FIRST element of every non-letter run (the
    # collapsed boundary); the wrapping zeros make edges uniform.
    prev_letter = np.concatenate(([True], is_letter[:-1]))
    keep = is_letter | prev_letter
    return vals[keep]


# Fixed-point scale for the log-prob table: scores are summed as exact int32
# millinats on both host and device, so detection decisions are bit-identical
# across the two paths (no float accumulation-order dependence).
SCORE_SCALE = 1000.0


class LangIdModel:
    """Hashed-trigram naive-Bayes detector over the fixed candidate set."""

    def __init__(self) -> None:
        self.table = self._build_table()  # [TABLE_SIZE, n_langs] float32 log-probs
        self.table_q = np.round(self.table * SCORE_SCALE).astype(np.int32)

    @staticmethod
    def _build_table() -> np.ndarray:
        from .langid_data import EXTRA_WORDS, TRAIN_TEXT

        n_langs = len(LANGUAGES)
        counts = np.zeros((TABLE_SIZE, n_langs), dtype=np.float64)
        for li, lang in enumerate(LANGUAGES):
            # Function-word inventories, Zipf-weighted by rank: anchors the
            # high-frequency grammar of each language.
            for rank, word in enumerate(_WORDS[lang]):
                weight = 1.0 / (rank + 1.0)
                cps = _normalize_codepoints(word)
                for i in range(len(cps) - 2):
                    h = _hash3(cps[i], cps[i + 1], cps[i + 2])
                    counts[h, li] += weight
                # Bigram/unigram shadows at shifted buckets add robustness for
                # short inputs without a second table.
                for i in range(len(cps) - 1):
                    h = _hash3(0, cps[i], cps[i + 1])
                    counts[h, li] += 0.3 * weight
                arr = np.asarray(cps, dtype=np.int64)
                np.add.at(counts[:, li], _word_hash_vec(arr), 0.5 * weight)
            # Curated news-vocabulary lexicon, flat-weighted: whole-word and
            # trigram mass for the orthography that separates the close
            # pairs (Danish ud-/-hed/fik vs Bokmål ut-/-het/fikk).
            for word in EXTRA_WORDS[lang].split():
                arr = np.asarray(_normalize_codepoints(word), dtype=np.int64)
                if arr.shape[0] >= 3:
                    np.add.at(counts[:, li], _hash3_vec(arr), 1.0)
                np.add.at(counts[:, li], _word_hash_vec(arr), 1.0)
            # Running-text trigram + whole-word profile: content-word
            # orthography — the signal that separates the close Scandinavian
            # pairs (Danish 'af/-tion/øj' vs Bokmål 'av/-sjon/øy' vs Nynorsk
            # 'ikkje/kva').
            cps = _normalize_codepoints(TRAIN_TEXT[lang])
            arr = np.asarray(cps, dtype=np.int64)
            np.add.at(counts[:, li], _hash3_vec(arr), 0.5)
            np.add.at(counts[:, li], _word_hash_vec(arr), 0.25)
        alpha = 0.01
        totals = counts.sum(axis=0, keepdims=True)
        logp = np.log((counts + alpha) / (totals + alpha * TABLE_SIZE))
        return logp.astype(np.float32)

    def scores_q(self, text: str) -> Optional[Tuple[np.ndarray, int]]:
        """(int32 millinat score totals ``[n_langs]``, feature count), or None
        for letterless text.  Features are the character trigrams plus one
        whole-word hash per word.  Integer sums — the device kernel computes
        the same values exactly (:mod:`textblaster_tpu.ops.langid_tpu`)."""
        arr = _normalize_vec(text)
        if arr.shape[0] < 3:
            return None
        h = _hash3_vec(arr)
        wh = _word_hash_vec(arr)
        scores = self.table_q[h].sum(axis=0, dtype=np.int64)
        if wh.shape[0]:
            scores = scores + self.table_q[wh].sum(axis=0, dtype=np.int64)
        return scores, len(h) + wh.shape[0]

    @staticmethod
    def decide_batch(
        scores_q: np.ndarray, n_grams: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized decision over ``scores_q [B, n_langs]`` / ``n_grams [B]``
        -> ``(winner index [B], confidence [B])``.

        Confidence is the softmax probability of the winner over the candidate
        set, on length-normalized log-likelihoods re-scaled by a bounded
        evidence factor — short texts stay uncertain, long unambiguous texts
        approach 1.0, mirroring lingua's behavior.  All arithmetic is float64
        and row-wise identical to the scalar form, so host and device
        finalizers decide bit-identically.
        """
        ng = np.maximum(np.asarray(n_grams, dtype=np.int64), 1).astype(np.float64)
        s = np.asarray(scores_q).astype(np.float64) / SCORE_SCALE
        # Quadratic damping for tiny inputs (a 2-trigram fragment must stay
        # uncertain however lopsided its per-trigram scores), capped growth
        # for long ones.
        evidence = np.minimum(ng, 400.0) * (ng / (ng + 25.0))
        z = (s / ng[:, None]) * evidence[:, None]
        z = z - z.max(axis=1, keepdims=True)
        # Bound the spread so the winner's softmax stays strictly below 1.0
        # in float64 — lingua never reports exactly 1.0 either, and the
        # min_confidence=1.0 configuration must filter everything
        # (language_filter.rs:74-82 semantics).
        z = np.maximum(z, -30.0)
        p = np.exp(z)
        p /= p.sum(axis=1, keepdims=True)
        best = p.argmax(axis=1)
        return best, p[np.arange(p.shape[0]), best]

    @staticmethod
    def decide(scores_q: np.ndarray, n_grams: int) -> Tuple[str, float]:
        """Scalar form of :meth:`decide_batch` (one document)."""
        best, conf = LangIdModel.decide_batch(
            np.asarray(scores_q)[None, :], np.array([n_grams])
        )
        return LANGUAGES[int(best[0])], float(conf[0])

    def detect(self, text: str) -> Optional[Tuple[str, float]]:
        scored = self.scores_q(text)
        if scored is None:
            return None
        return self.decide(*scored)


_MODEL: Optional[LangIdModel] = None


def get_model() -> LangIdModel:
    """Process-wide model instance (profiles built once, reused everywhere —
    unlike the reference, which rebuilds its detector per document,
    language_filter.rs:39-46)."""
    global _MODEL
    if _MODEL is None:
        _MODEL = LangIdModel()
    return _MODEL
