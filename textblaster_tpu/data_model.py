"""Core data model: the document record and the per-document processing outcome.

TPU-native re-design of the reference's data plane (reference:
``/root/reference/src/data_model.rs:5-34``).  The reference moves one
``TextDocument`` at a time as JSON over RabbitMQ; here the same record is the
*host-side* view of a document, while on device documents live as packed ragged
UTF-8 byte tensors (see :mod:`textblaster_tpu.ops.packing`).  ``TextDocument``
and ``ProcessingOutcome`` keep the reference's exact JSON wire format (serde
externally-tagged enums) so corpora and results interop bit-for-bit.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from datetime import date, datetime
from typing import Any, Dict, Optional, Tuple

__all__ = ["TextDocument", "ProcessingOutcome"]

# serde formats for chrono NaiveDate / NaiveDateTime (reference data_model.rs:10-11):
#   NaiveDate      -> "2024-01-31"
#   NaiveDateTime  -> "2024-01-31T12:34:56" (optionally ".%f")
_DATE_FMT = "%Y-%m-%d"


def _parse_naive_datetime(s: str) -> datetime:
    # chrono serializes NaiveDateTime as ISO-8601 without timezone.
    return datetime.fromisoformat(s)


def _fmt_naive_datetime(dt: datetime) -> str:
    """chrono ``%Y-%m-%dT%H:%M:%S%.f``: fraction trimmed to 3/6 digit groups
    (nothing when zero) so output is byte-identical to serde_json."""
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    us = dt.microsecond
    if us == 0:
        return base
    if us % 1000 == 0:
        return f"{base}.{us // 1000:03d}"
    return f"{base}.{us:06d}"


@dataclass
class TextDocument:
    """One document flowing through the pipeline.

    Mirrors ``TextDocument`` (reference ``src/data_model.rs:5-13``): ``id``,
    ``content``, ``source``, optional ``added`` date, optional ``created``
    (start, end) datetime pair, and a flat string->string ``metadata`` map that
    filters stamp status/reason/stat entries into.
    """

    id: str = ""
    content: str = ""
    source: str = ""
    added: Optional[date] = None
    created: Optional[Tuple[datetime, datetime]] = None
    metadata: Dict[str, str] = field(default_factory=dict)

    # --- serde-compatible JSON (wire format parity with the reference) ---

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "content": self.content,
            "source": self.source,
            "added": self.added.strftime(_DATE_FMT) if self.added else None,
            "created": (
                [_fmt_naive_datetime(self.created[0]), _fmt_naive_datetime(self.created[1])]
                if self.created
                else None
            ),
            "metadata": dict(self.metadata),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TextDocument":
        added = d.get("added")
        created = d.get("created")
        return cls(
            id=d["id"],
            content=d["content"],
            source=d.get("source", ""),
            added=datetime.strptime(added, _DATE_FMT).date() if added else None,
            created=(
                (_parse_naive_datetime(created[0]), _parse_naive_datetime(created[1]))
                if created
                else None
            ),
            metadata=dict(d.get("metadata") or {}),
        )

    def to_json(self) -> str:
        # serde_json emits no whitespace; keep the bytes identical.
        return json.dumps(self.to_dict(), ensure_ascii=False, separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str | bytes) -> "TextDocument":
        return cls.from_dict(json.loads(s))

    def copy(self) -> "TextDocument":
        return TextDocument(
            id=self.id,
            content=self.content,
            source=self.source,
            added=self.added,
            created=self.created,
            metadata=dict(self.metadata),
        )


@dataclass
class ProcessingOutcome:
    """Per-document outcome (reference ``src/data_model.rs:19-34``).

    One of three kinds:
      * ``Success`` — passed every pipeline step;
      * ``Filtered`` — dropped by a step, with a human-readable ``reason``;
      * ``Error`` — a step raised a hard error (``error_message`` +
        ``worker_id``).

    JSON layout matches serde's externally-tagged enum encoding, e.g.
    ``{"Filtered": {"document": {...}, "reason": "..."}}``.
    """

    SUCCESS = "Success"
    FILTERED = "Filtered"
    ERROR = "Error"

    kind: str = SUCCESS
    document: TextDocument = field(default_factory=TextDocument)
    reason: str = ""
    error_message: str = ""
    worker_id: str = ""

    @classmethod
    def success(cls, document: TextDocument) -> "ProcessingOutcome":
        return cls(kind=cls.SUCCESS, document=document)

    @classmethod
    def filtered(cls, document: TextDocument, reason: str) -> "ProcessingOutcome":
        return cls(kind=cls.FILTERED, document=document, reason=reason)

    @classmethod
    def error(
        cls, document: TextDocument, error_message: str, worker_id: str
    ) -> "ProcessingOutcome":
        return cls(
            kind=cls.ERROR,
            document=document,
            error_message=error_message,
            worker_id=worker_id,
        )

    def to_dict(self) -> Dict[str, Any]:
        if self.kind == self.SUCCESS:
            return {"Success": self.document.to_dict()}
        if self.kind == self.FILTERED:
            return {"Filtered": {"document": self.document.to_dict(), "reason": self.reason}}
        return {
            "Error": {
                "document": self.document.to_dict(),
                "error_message": self.error_message,
                "worker_id": self.worker_id,
            }
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ProcessingOutcome":
        if "Success" in d:
            return cls.success(TextDocument.from_dict(d["Success"]))
        if "Filtered" in d:
            inner = d["Filtered"]
            return cls.filtered(TextDocument.from_dict(inner["document"]), inner["reason"])
        if "Error" in d:
            inner = d["Error"]
            return cls.error(
                TextDocument.from_dict(inner["document"]),
                inner["error_message"],
                inner["worker_id"],
            )
        raise ValueError(f"Unknown ProcessingOutcome variant: {list(d)}")

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), ensure_ascii=False, separators=(",", ":"))

    @classmethod
    def from_json(cls, s: str | bytes) -> "ProcessingOutcome":
        return cls.from_dict(json.loads(s))
