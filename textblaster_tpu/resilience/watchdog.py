"""Stall watchdog: per-stage deadlines over the host-side blocking waits.

The rest of the resilience stack handles failures that *raise*; this module
handles failures that *hang*.  The overlapped host pipeline has four
blocking seams where a wedged dependency stalls a rank silently — the
device fetch (``jax.device_get`` / ``block_until_ready`` on an XLA dispatch
that never completes), pack-pool futures, the write-behind queue, and the
reader prefetch queue.  Without supervision the rest of a lockstep gang
only discovers such a stall through the blunt cross-host exchange deadline,
which kills the run instead of recovering it.

:class:`StageWatchdog` deadline-bounds each stage.  Every bounded wait is a
*polling* loop with a short tick, so the wait stays interruptible: when the
stage deadline expires the watchdog raises a typed
:class:`~textblaster_tpu.errors.StallError` naming the stage, the elapsed
time, and the deadline.  ``StallError`` is classified retryable, so a
device-fetch stall enters the ordinary retry → split-half → host-oracle
degradation ladder exactly like a raised fault, and on the lockstep path it
converts to a local fault verdict so the gang jointly drains the window.

Inert by default: every production seam guards its watchdog branch with a
single ``if WATCHDOG.enabled:`` attribute check and keeps the original
unbounded wait in the ``else`` arm — a disabled watchdog (the default;
``--stage-deadline-s 0``) adds exactly one attribute read per seam and
never constructs a beat, timestamp, or closure.

The deadline knob is *scheduling-only*: it cannot change any document
decision or output byte, so it is excluded from AOT compile-cache keys and
only named in the profiler's env-drift notes (like ``TEXTBLAST_SPECULATE``).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Iterable, Iterator, Mapping, Optional

from ..errors import StallError
from ..utils.events import EVENTS
from ..utils.metrics import METRICS
from ..utils.trace import TRACER

__all__ = ["ENV_KNOB", "STAGES", "StageWatchdog", "WATCHDOG"]

#: The four supervised host-side stages, in pipeline order.
STAGES = ("device_fetch", "pack_wait", "write_queue", "read_prefetch")

#: Environment knob: default per-stage deadline in seconds (0 disables).
ENV_KNOB = "TEXTBLAST_STAGE_DEADLINE_S"

#: Poll interval for bounded waits.  Short enough that an expired deadline
#: surfaces promptly; long enough that the enabled-path overhead stays in
#: the noise next to real device/queue latencies.
_TICK_S = 0.02


class _Beat:
    """A thread-local heartbeat: 'this thread is inside *stage* since
    *start*'.  The fault injector's latency kinds (``delay=``/``hang``)
    consult the current beat so an injected hang can be rescued by the
    stage deadline on the hanging thread itself — no monitor thread."""

    __slots__ = ("stage", "start", "deadline_s")

    def __init__(self, stage: str, start: float, deadline_s: float) -> None:
        self.stage = stage
        self.start = start
        self.deadline_s = deadline_s


class StageWatchdog:
    """Deadline supervisor for the host-side pipeline stages.

    One process-global instance (:data:`WATCHDOG`) is shared by every seam;
    ``configure()`` arms it (CLI ``--stage-deadline-s`` or the
    ``TEXTBLAST_STAGE_DEADLINE_S`` env knob), ``reset()`` disarms for tests.
    """

    def __init__(self) -> None:
        self.enabled = False
        self._default_s = 0.0
        self._per_stage: Dict[str, float] = {}
        self._tls = threading.local()

    # -- configuration -----------------------------------------------------

    def configure(
        self,
        deadline_s: float,
        per_stage: Optional[Mapping[str, float]] = None,
    ) -> None:
        """Arm (deadline > 0) or disarm (deadline <= 0) the watchdog.

        ``per_stage`` overrides the default deadline for individual stages.
        Publishes one ``watchdog_deadline_seconds_<stage>`` gauge per stage
        when armed so the run report records the active deadlines.
        """
        self._default_s = max(0.0, float(deadline_s))
        self._per_stage = {
            str(k): max(0.0, float(v)) for k, v in (per_stage or {}).items()
        }
        self.enabled = self._default_s > 0 or any(
            v > 0 for v in self._per_stage.values()
        )
        if self.enabled:
            for stage in STAGES:
                METRICS.set(
                    "watchdog_deadline_seconds_" + stage,
                    self.deadline_for(stage),
                )

    def configure_from_env(self, env: Optional[Mapping[str, str]] = None) -> None:
        """Arm from ``TEXTBLAST_STAGE_DEADLINE_S`` (unset/invalid → leave
        the current configuration alone)."""
        import os

        raw = (env if env is not None else os.environ).get(ENV_KNOB)
        if raw is None or not str(raw).strip():
            return
        try:
            self.configure(float(raw))
        except (TypeError, ValueError):
            return

    def reset(self) -> None:
        """Disarm and forget per-stage overrides (test hygiene)."""
        self.enabled = False
        self._default_s = 0.0
        self._per_stage = {}
        self._tls = threading.local()

    def deadline_for(self, stage: str) -> float:
        """Effective deadline for *stage* in seconds (0 = unbounded)."""
        return self._per_stage.get(stage, self._default_s)

    # -- stall bookkeeping -------------------------------------------------

    def stall(
        self, stage: str, elapsed_s: float, deadline_s: float, detail: str = ""
    ) -> None:
        """Record a stall and raise the typed :class:`StallError`."""
        METRICS.inc("watchdog_stalls_total")
        TRACER.instant(
            "watchdog_stall",
            {
                "stage": stage,
                "elapsed_s": round(elapsed_s, 3),
                "deadline_s": deadline_s,
                "detail": detail,
            },
        )
        if EVENTS.enabled:
            EVENTS.emit("watchdog_stall", stage=stage,
                        elapsed_s=round(elapsed_s, 3),
                        deadline_s=deadline_s, detail=detail)
        raise StallError(
            stage, elapsed_s=elapsed_s, deadline_s=deadline_s, detail=detail
        )

    def escalated(self, exc: BaseException) -> None:
        """Count a stall handed to existing recovery machinery (retry
        ladder, negotiated fault verdict).  No-op for non-stall errors so
        callers can report every retryable exception unconditionally."""
        if isinstance(exc, StallError):
            METRICS.inc("watchdog_escalations_total")
            TRACER.instant("watchdog_escalation", {"stage": exc.stage})
            if EVENTS.enabled:
                EVENTS.emit("watchdog_escalation", reason=exc.stage)

    # -- heartbeats (fault-injector integration) ---------------------------

    @contextmanager
    def stage_beat(self, stage: str) -> Iterator[None]:
        """Mark this thread as inside *stage* for the dynamic extent.

        The fault injector's ``delay``/``hang`` kinds poll the current beat
        so an injected hang raises ``StallError`` on its own thread when
        the stage deadline expires.
        """
        prev = getattr(self._tls, "beat", None)
        self._tls.beat = _Beat(stage, time.monotonic(), self.deadline_for(stage))
        try:
            yield
        finally:
            self._tls.beat = prev

    def current_beat(self) -> Optional[_Beat]:
        return getattr(self._tls, "beat", None)

    def check_beat(self, detail: str = "") -> None:
        """Raise ``StallError`` iff this thread's beat deadline expired."""
        beat = self.current_beat()
        if beat is None or beat.deadline_s <= 0:
            return
        elapsed = time.monotonic() - beat.start
        if elapsed >= beat.deadline_s:
            self.stall(beat.stage, elapsed, beat.deadline_s, detail)

    # -- bounded waits -----------------------------------------------------

    def wait(
        self,
        stage: str,
        done: Callable[[], bool],
        detail: Optional[Callable[[], str]] = None,
    ) -> None:
        """Poll ``done()`` until true; raise ``StallError`` at the stage
        deadline.  With an unbounded stage (deadline 0) returns at once so
        the caller falls through to its ordinary blocking wait."""
        deadline_s = self.deadline_for(stage)
        if deadline_s <= 0:
            return
        start = time.monotonic()
        while not done():
            elapsed = time.monotonic() - start
            if elapsed >= deadline_s:
                self.stall(
                    stage, elapsed, deadline_s, detail() if detail else ""
                )
            time.sleep(_TICK_S)

    def wait_device_ready(self, stage: str, leaves: Iterable[object]) -> None:
        """Bounded readiness wait over device arrays (duck-typed via
        ``jax.Array.is_ready``) so the subsequent ``device_get`` /
        ``block_until_ready`` cannot block past the stage deadline.  Leaves
        without ``is_ready`` (host numpy, scalars) are already 'ready'."""
        pending = [a for a in leaves if hasattr(a, "is_ready")]
        if not pending:
            return
        self.wait(
            stage,
            lambda: all(a.is_ready() for a in pending),
            lambda: f"{len(pending)} device array(s) in flight",
        )

    def queue_get(self, stage: str, q: "queue_mod.Queue") -> object:
        """``q.get()`` bounded by the stage deadline."""
        deadline_s = self.deadline_for(stage)
        if deadline_s <= 0:
            return q.get()
        start = time.monotonic()
        while True:
            try:
                return q.get(timeout=min(0.1, deadline_s))
            except queue_mod.Empty:
                elapsed = time.monotonic() - start
                if elapsed >= deadline_s:
                    self.stall(
                        stage,
                        elapsed,
                        deadline_s,
                        f"queue depth {q.qsize()}",
                    )

    def queue_put(self, stage: str, q: "queue_mod.Queue", item: object) -> None:
        """``q.put(item)`` bounded by the stage deadline."""
        deadline_s = self.deadline_for(stage)
        if deadline_s <= 0:
            q.put(item)
            return
        start = time.monotonic()
        while True:
            try:
                q.put(item, timeout=min(0.1, deadline_s))
                return
            except queue_mod.Full:
                elapsed = time.monotonic() - start
                if elapsed >= deadline_s:
                    self.stall(
                        stage,
                        elapsed,
                        deadline_s,
                        f"queue depth {q.qsize()}",
                    )

    def join_thread(
        self, stage: str, thread: "threading.Thread", progress: Callable[[], int]
    ) -> None:
        """Bounded, progress-aware ``thread.join()``.

        The deadline is a *no-progress* bound: each time ``progress()``
        moves (e.g. the write queue drains an item) the timer restarts, so
        a slow-but-live drain is never killed while a wedged one surfaces a
        typed ``StallError`` carrying the residual depth.  Used for the
        writer teardown, where an unbounded join could wedge shutdown
        forever.  Falls back to a generous static bound when the watchdog
        is disarmed — teardown is off the hot path, so the bounded join is
        unconditional.
        """
        deadline_s = self.deadline_for(stage)
        if deadline_s <= 0:
            deadline_s = 60.0
        last = progress()
        start = time.monotonic()
        while thread.is_alive():
            thread.join(timeout=min(0.1, deadline_s))
            now_progress = progress()
            if now_progress != last:
                last = now_progress
                start = time.monotonic()
                continue
            elapsed = time.monotonic() - start
            if elapsed >= deadline_s:
                self.stall(
                    stage,
                    elapsed,
                    deadline_s,
                    f"queue depth {now_progress}",
                )


#: Process-global watchdog shared by every supervised seam.
WATCHDOG = StageWatchdog()
