"""Fault-tolerant execution layer.

The reference's only fault story is RabbitMQ redelivery plus "swallow hard
errors and surface a count mismatch" (SURVEY.md §7 quirk #2).  This package
gives the broker-free executor a real one:

* :mod:`~textblaster_tpu.resilience.retry` — :class:`RetryPolicy` with
  exponential backoff + jitter, an injectable clock/sleep, and an error
  classifier that distinguishes transient device/IO faults (retryable) from
  deterministic pipeline errors (not), applied at the three guarded seams:
  Parquet row-group reads, device batch execution, checkpoint commit;
* :mod:`~textblaster_tpu.resilience.breaker` — :class:`CircuitBreaker`
  behind the device degradation ladder (retry the batch -> split it in half
  -> rerun on the host oracle), tripping the whole run to the host backend
  after N consecutive device failures;
* :mod:`~textblaster_tpu.resilience.faults` — :data:`FAULTS`, a
  process-global, test-armable :class:`FaultInjector` planted at every seam
  the retry layer guards, so chaos tests drive real control flow instead of
  monkeypatching internals;
* :mod:`~textblaster_tpu.resilience.deadletter` — :class:`DeadLetterSink`,
  the opt-in ``--errors-file`` Parquet quarantine for Error outcomes and
  unreadable rows (the default remains the reference's neither-file
  behavior);
* :mod:`~textblaster_tpu.resilience.negotiated` — :class:`NegotiatedGuard`,
  the multi-host arm of the ladder: per lockstep round every host
  allgathers a fault flag and ALL hosts jointly retry (shared zero-jitter
  backoff), then jointly degrade the round to the host oracle, with
  per-bucket breakers latched by the shared verdict sequence;
* :mod:`~textblaster_tpu.resilience.watchdog` — :data:`WATCHDOG`, the
  stall watchdog: per-stage deadlines over the host-side blocking waits
  (device fetch, pack futures, write-behind queue, reader prefetch) that
  raise a typed :class:`StallError` instead of hanging forever, escalating
  through the same retry ladder / negotiated fault verdicts as raised
  faults;
* :mod:`~textblaster_tpu.resilience.membership` — elastic gang membership:
  renewable liveness leases (KV store for lockstep runs, shared-filesystem
  files for ``--elastic``), membership epochs that bump when the gang
  shrinks/grows, deterministic stripe ownership with lowest-live-rank
  adoption, and the typed :class:`PeerFailure` a deadline-bounded exchange
  raises instead of hanging on a dead peer.
"""

from .breaker import CircuitBreaker
from .deadletter import (
    DEADLETTER_SCHEMA,
    DeadLetterSink,
    outcome_row,
    read_error_row,
)
from .faults import FAULTS, FaultInjector, arm_from_env
from .membership import (
    EpochTracker,
    FileMembershipStore,
    KVLeaseStore,
    LeaseHeartbeat,
    MembershipConfig,
    PeerFailure,
    stripe_owner,
)
from .negotiated import NegotiatedGuard
from .retry import (
    RetryPolicy,
    classify_error,
    is_oom_error,
    is_retryable_error,
)
from .watchdog import WATCHDOG, StageWatchdog

__all__ = [
    "CircuitBreaker",
    "DEADLETTER_SCHEMA",
    "DeadLetterSink",
    "EpochTracker",
    "FAULTS",
    "FaultInjector",
    "FileMembershipStore",
    "KVLeaseStore",
    "LeaseHeartbeat",
    "MembershipConfig",
    "NegotiatedGuard",
    "PeerFailure",
    "RetryPolicy",
    "StageWatchdog",
    "WATCHDOG",
    "arm_from_env",
    "classify_error",
    "is_oom_error",
    "is_retryable_error",
    "outcome_row",
    "read_error_row",
    "stripe_owner",
]
