"""Process-global fault injector for chaos tests.

Every seam the retry layer guards calls :meth:`FaultInjector.fire` with its
site name; an armed fault acts through the *production* control flow, so
chaos tests exercise exactly the code paths a real failure would — no
monkeypatching of internals.

Three fault *kinds*:

* ``raise`` (the default) — the armed exception propagates from the seam;
* ``delay`` — sleep ``delay_ms`` then proceed, modelling a slow dependency
  (a delay longer than the stage deadline surfaces as a watchdog
  :class:`~textblaster_tpu.errors.StallError`);
* ``hang`` — block indefinitely, modelling a wedged dependency: the hang
  only ends when the stall watchdog's stage deadline expires on the
  hanging thread (raising ``StallError`` into the seam) or the injector is
  disarmed (:meth:`FaultInjector.reset` from another thread).

Sites planted in this build:

* ``"read.batch"``        — per row-group Parquet fetch
  (:mod:`textblaster_tpu.io.parquet_reader`);
* ``"device.execute"``    — per device-batch dispatch
  (:meth:`textblaster_tpu.ops.pipeline.CompiledPipeline.dispatch_batch`,
  and the lockstep launch in
  :meth:`~textblaster_tpu.ops.pipeline.CompiledPipeline.dispatch_lockstep`
  so device hangs are injectable on the multi-host path too);
* ``"checkpoint.commit"`` — per checkpoint cursor commit
  (:meth:`textblaster_tpu.checkpoint.CheckpointState.save`);
* ``"multihost.round"``   — per multi-host lockstep round launch
  (:meth:`textblaster_tpu.ops.pipeline.CompiledPipeline.dispatch_lockstep`);
* ``"multihost.lease"``   — per liveness-lease renewal
  (:mod:`textblaster_tpu.resilience.membership`, KV and file backends — an
  armed fault makes this process's lease go stale, so peers evict it);
* ``"multihost.rejoin"``  — per stripe-cursor claim/adoption
  (:meth:`textblaster_tpu.checkpoint.CheckpointState.adopt` on the
  ``--elastic`` path);
* ``"multihost.exchange.post"`` — per exchange-slot post on the file-lease
  transport (:meth:`FileMembershipStore.post_exchange_slot` — an armed
  fault makes this rank's exchange row never appear, so peers hit the
  deadline and, under ``--survive-peer-loss``, reform around it);
* ``"multihost.reform"``  — per reformation election attempt
  (:func:`textblaster_tpu.resilience.membership.elect_members`), so the
  reformation protocol itself is chaos-testable;
* ``"multihost.join.post"`` — per join-request post
  (:meth:`FileMembershipStore.post_join_request` — an armed fault kills a
  joiner before its request lands, so the gang never sees it and proceeds
  un-grown);
* ``"multihost.join.admit"`` — per admission observation on the gang side
  (a member noticing a valid join request, on both the lockstep
  phase-boundary path and the ``--elastic`` loop — an armed fault makes
  one member die mid-admission, folding into the reformation retry);
* ``"multihost.speculate"`` — per speculative cross-phase launch at a
  lockstep phase barrier (``run_local_shard``'s ``launch``
  with ``speculative=True`` — an armed fault marks the speculated round
  launch-faulted, so its verdict convenes at the round's adoption slot and
  chaos tests can pin the joint-rollback/re-dispatch path).

The injector is **inert by default**: with nothing armed, :meth:`fire` is a
single attribute load + falsy check and keeps no per-call state, so
production paths pay effectively nothing (a tier-1 guard test pins this).

Multi-host chaos tests run each rank as a separate OS process, so arming
can't happen in the test process: :func:`arm_from_env` reads a
``TEXTBLAST_FAULTS`` spec from the environment inside the subprocess (and
``TEXTBLAST_FAULTS_PROCESS`` gates it to one rank) — the only way to fault
exactly one host of a real 2-process run.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Union

__all__ = ["FaultInjector", "FAULTS", "arm_from_env"]

ExcSpec = Union[BaseException, Callable[[], BaseException]]

#: Poll interval for the latency kinds — short enough that disarm and
#: deadline expiry surface promptly inside an injected delay/hang.
_LATENCY_TICK_S = 0.01


@dataclass
class _ArmedFault:
    """One armed fault: skip ``after_calls`` fires, then trigger ``times``.

    ``kind`` selects what a trigger does: ``"raise"`` raises ``exc``,
    ``"delay"`` sleeps ``delay_ms`` then proceeds, ``"hang"`` blocks until
    the watchdog beat deadline or a disarm.  ``raised`` counts triggers of
    every kind (the name predates the latency kinds; :meth:`fired` reads
    it either way).
    """

    exc: Optional[ExcSpec]
    after_calls: int = 0
    times: int = 1
    kind: str = "raise"
    delay_ms: float = 0.0
    seen: int = 0
    raised: int = 0

    def should_raise(self) -> bool:
        return self.seen > self.after_calls and self.raised < self.times

    def make_exc(self) -> BaseException:
        if callable(self.exc) and not isinstance(self.exc, BaseException):
            return self.exc()
        return self.exc


class FaultInjector:
    """Test-armable fault hook (``inject(site, after_calls=k, exc=...)``).

    ``times`` controls how many consecutive fires raise once triggered —
    ``times=1`` models a transient blip (first retry succeeds), a large
    ``times`` models a persistent outage (the ladder degrades rung by rung).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Falsy when nothing is armed — the only state `fire` consults on
        # the production fast path.
        self._sites: Dict[str, List[_ArmedFault]] = {}
        # Bumped by reset(): a thread blocked inside an injected hang polls
        # this and unblocks when the arming that started it is gone.
        self._generation = 0

    # --- arming (test-side) -------------------------------------------------

    def inject(
        self,
        site: str,
        exc: Optional[ExcSpec] = None,
        after_calls: int = 0,
        times: int = 1,
        kind: str = "raise",
        delay_ms: float = 0.0,
    ) -> None:
        """Arm ``site``: the ``after_calls+1``-th fire (and the ``times-1``
        following it) trigger the fault.  For the default ``kind="raise"``,
        ``exc`` may be an exception instance (re-raised each time) or a
        zero-arg factory; ``kind="delay"`` sleeps ``delay_ms`` then
        proceeds; ``kind="hang"`` blocks until the watchdog stage deadline
        or :meth:`reset`."""
        if times < 1:
            raise ValueError("times must be >= 1")
        if after_calls < 0:
            raise ValueError("after_calls must be >= 0")
        if kind not in ("raise", "delay", "hang"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if kind == "raise" and exc is None:
            raise ValueError("kind='raise' requires exc")
        if kind == "delay" and delay_ms <= 0:
            raise ValueError("kind='delay' requires delay_ms > 0")
        with self._lock:
            self._sites.setdefault(site, []).append(
                _ArmedFault(
                    exc=exc,
                    after_calls=after_calls,
                    times=times,
                    kind=kind,
                    delay_ms=delay_ms,
                )
            )

    def reset(self) -> None:
        """Disarm everything (test teardown); unblocks in-flight hangs."""
        with self._lock:
            self._sites = {}
            self._generation += 1

    def active(self) -> bool:
        """True if any fault is armed (the tier-1 inertness guard)."""
        return bool(self._sites)

    def fired(self, site: str) -> int:
        """How many times ``site``'s armed faults have raised so far."""
        with self._lock:
            return sum(f.raised for f in self._sites.get(site, ()))

    # --- production side ----------------------------------------------------

    def fire(self, site: str) -> None:
        """Called by production seams.  Inert (one falsy check) unless a
        test armed a fault for ``site``.  Latency kinds (delay/hang) block
        *outside* the injector lock so other sites and the disarm path
        stay live while a seam sleeps."""
        if not self._sites:
            return
        action = None
        with self._lock:
            faults = self._sites.get(site)
            if not faults:
                return
            for f in faults:
                f.seen += 1
                if f.should_raise():
                    f.raised += 1
                    if f.kind == "raise":
                        action = ("raise", f.make_exc())
                    elif f.kind == "delay":
                        action = ("delay", f.delay_ms)
                    else:
                        action = ("hang", self._generation)
                    break
            else:
                return
        if action[0] == "raise":
            raise action[1]
        if action[0] == "delay":
            self._injected_delay(site, action[1] / 1000.0)
        else:
            self._injected_hang(site, action[1])

    def _injected_delay(self, site: str, seconds: float) -> None:
        """Sleep in watchdog-aware ticks, then let the seam proceed.  A
        delay longer than the supervised stage's deadline surfaces as a
        ``StallError`` on this thread mid-sleep."""
        from .watchdog import WATCHDOG

        end = time.monotonic() + seconds
        while True:
            WATCHDOG.check_beat(f"injected delay at {site}")
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(_LATENCY_TICK_S, remaining))

    def _injected_hang(self, site: str, generation: int) -> None:
        """Block until the watchdog stage deadline expires on this thread
        (raising ``StallError`` into the seam) or :meth:`reset` disarms the
        fault that started the hang."""
        from .watchdog import WATCHDOG

        while True:
            WATCHDOG.check_beat(f"injected hang at {site}")
            with self._lock:
                if self._generation != generation:
                    return
            time.sleep(_LATENCY_TICK_S)


#: The process-global injector every guarded seam fires into.
FAULTS = FaultInjector()

#: Exception types :func:`arm_from_env` may construct — an allowlist, not
#: ``eval``: the env var names one of these, never arbitrary code.
_ENV_EXC_TYPES = {
    "OSError": OSError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "MemoryError": MemoryError,
}


def arm_from_env(
    env: Optional[Dict[str, str]] = None,
    process_id: Optional[int] = None,
    injector: Optional[FaultInjector] = None,
) -> int:
    """Arm :data:`FAULTS` from a ``TEXTBLAST_FAULTS`` environment spec.

    Spec grammar (``;``-separated entries)::

        site[:after=N][:times=M][:exc=Name | :delay=MS | :hang]

    e.g. ``TEXTBLAST_FAULTS="multihost.round:after=1:times=2"`` arms an
    ``OSError`` (the default — classified retryable) on the second and third
    fires of the lockstep-round seam.  ``exc`` must name a type in the
    allowlist (OSError, TimeoutError, RuntimeError, MemoryError).

    The three kind options are mutually exclusive per entry: ``exc=Name``
    raises, ``delay=MS`` sleeps that many milliseconds then proceeds, and
    ``hang`` blocks until the stall watchdog's stage deadline or a disarm
    (``device.execute:hang`` is how the hang-chaos tests wedge one rank's
    device dispatch).  Entries with none of the three keep the historical
    raise-``OSError`` default, so exception-only specs parse identically
    to the pre-latency grammar.

    When ``TEXTBLAST_FAULTS_PROCESS`` is set and ``process_id`` is given,
    arming is skipped unless they match — how a multi-host chaos test faults
    exactly one rank of a real N-process run.  Returns the number of faults
    armed (0 when the spec is absent or gated off).
    """
    import os

    env = os.environ if env is None else env
    injector = FAULTS if injector is None else injector
    spec = env.get("TEXTBLAST_FAULTS", "").strip()
    if not spec:
        return 0
    only = env.get("TEXTBLAST_FAULTS_PROCESS", "").strip()
    if only and process_id is not None and int(only) != int(process_id):
        return 0
    armed = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site, after_calls, times = parts[0], 0, 1
        exc_name: Optional[str] = None
        delay_ms: Optional[float] = None
        hang = False
        for p in parts[1:]:
            key, _, val = p.partition("=")
            if key == "after":
                after_calls = int(val)
            elif key == "times":
                times = int(val)
            elif key == "exc":
                exc_name = val
            elif key == "delay":
                delay_ms = float(val)
                if delay_ms <= 0:
                    raise ValueError(
                        f"TEXTBLAST_FAULTS delay must be > 0 ms in {entry!r}"
                    )
            elif key == "hang":
                if val not in ("", "1", "true"):
                    raise ValueError(
                        f"TEXTBLAST_FAULTS hang takes no value in {entry!r}"
                    )
                hang = True
            else:
                raise ValueError(
                    f"unknown TEXTBLAST_FAULTS option {key!r} in {entry!r}"
                )
        if (exc_name is not None) + (delay_ms is not None) + hang > 1:
            raise ValueError(
                f"TEXTBLAST_FAULTS entry mixes fault kinds "
                f"(exc/delay/hang are mutually exclusive) in {entry!r}"
            )
        if delay_ms is not None:
            injector.inject(
                site,
                after_calls=after_calls,
                times=times,
                kind="delay",
                delay_ms=delay_ms,
            )
        elif hang:
            injector.inject(
                site, after_calls=after_calls, times=times, kind="hang"
            )
        else:
            try:
                exc_type = _ENV_EXC_TYPES[exc_name or "OSError"]
            except KeyError:
                raise ValueError(
                    f"TEXTBLAST_FAULTS exc must be one of "
                    f"{sorted(_ENV_EXC_TYPES)}, got {exc_name!r}"
                ) from None
            injector.inject(
                site,
                lambda site=site, exc_type=exc_type: exc_type(
                    f"injected fault at {site} (TEXTBLAST_FAULTS)"
                ),
                after_calls=after_calls,
                times=times,
            )
        armed += 1
    return armed
