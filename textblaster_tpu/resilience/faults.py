"""Process-global fault injector for chaos tests.

Every seam the retry layer guards calls :meth:`FaultInjector.fire` with its
site name; an armed fault raises through the *production* control flow, so
chaos tests exercise exactly the code paths a real transient failure would —
no monkeypatching of internals.

Sites planted in this build:

* ``"read.batch"``        — per row-group Parquet fetch
  (:mod:`textblaster_tpu.io.parquet_reader`);
* ``"device.execute"``    — per device-batch dispatch
  (:meth:`textblaster_tpu.ops.pipeline.CompiledPipeline.dispatch_batch`);
* ``"checkpoint.commit"`` — per checkpoint cursor commit
  (:meth:`textblaster_tpu.checkpoint.CheckpointState.save`);
* ``"multihost.round"``   — per multi-host lockstep round launch
  (:meth:`textblaster_tpu.ops.pipeline.CompiledPipeline.dispatch_lockstep`);
* ``"multihost.lease"``   — per liveness-lease renewal
  (:mod:`textblaster_tpu.resilience.membership`, KV and file backends — an
  armed fault makes this process's lease go stale, so peers evict it);
* ``"multihost.rejoin"``  — per stripe-cursor claim/adoption
  (:meth:`textblaster_tpu.checkpoint.CheckpointState.adopt` on the
  ``--elastic`` path);
* ``"multihost.exchange.post"`` — per exchange-slot post on the file-lease
  transport (:meth:`FileMembershipStore.post_exchange_slot` — an armed
  fault makes this rank's exchange row never appear, so peers hit the
  deadline and, under ``--survive-peer-loss``, reform around it);
* ``"multihost.reform"``  — per reformation election attempt
  (:func:`textblaster_tpu.resilience.membership.elect_members`), so the
  reformation protocol itself is chaos-testable;
* ``"multihost.join.post"`` — per join-request post
  (:meth:`FileMembershipStore.post_join_request` — an armed fault kills a
  joiner before its request lands, so the gang never sees it and proceeds
  un-grown);
* ``"multihost.join.admit"`` — per admission observation on the gang side
  (a member noticing a valid join request, on both the lockstep
  phase-boundary path and the ``--elastic`` loop — an armed fault makes
  one member die mid-admission, folding into the reformation retry);
* ``"multihost.speculate"`` — per speculative cross-phase launch at a
  lockstep phase barrier (``run_local_shard``'s ``launch``
  with ``speculative=True`` — an armed fault marks the speculated round
  launch-faulted, so its verdict convenes at the round's adoption slot and
  chaos tests can pin the joint-rollback/re-dispatch path).

The injector is **inert by default**: with nothing armed, :meth:`fire` is a
single attribute load + falsy check and keeps no per-call state, so
production paths pay effectively nothing (a tier-1 guard test pins this).

Multi-host chaos tests run each rank as a separate OS process, so arming
can't happen in the test process: :func:`arm_from_env` reads a
``TEXTBLAST_FAULTS`` spec from the environment inside the subprocess (and
``TEXTBLAST_FAULTS_PROCESS`` gates it to one rank) — the only way to fault
exactly one host of a real 2-process run.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Union

__all__ = ["FaultInjector", "FAULTS", "arm_from_env"]

ExcSpec = Union[BaseException, Callable[[], BaseException]]


@dataclass
class _ArmedFault:
    """One armed fault: skip ``after_calls`` fires, then raise ``times``."""

    exc: ExcSpec
    after_calls: int = 0
    times: int = 1
    seen: int = 0
    raised: int = 0

    def should_raise(self) -> bool:
        return self.seen > self.after_calls and self.raised < self.times

    def make_exc(self) -> BaseException:
        if callable(self.exc) and not isinstance(self.exc, BaseException):
            return self.exc()
        return self.exc


class FaultInjector:
    """Test-armable fault hook (``inject(site, after_calls=k, exc=...)``).

    ``times`` controls how many consecutive fires raise once triggered —
    ``times=1`` models a transient blip (first retry succeeds), a large
    ``times`` models a persistent outage (the ladder degrades rung by rung).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Falsy when nothing is armed — the only state `fire` consults on
        # the production fast path.
        self._sites: Dict[str, List[_ArmedFault]] = {}

    # --- arming (test-side) -------------------------------------------------

    def inject(
        self,
        site: str,
        exc: ExcSpec,
        after_calls: int = 0,
        times: int = 1,
    ) -> None:
        """Arm ``site``: the ``after_calls+1``-th fire (and the ``times-1``
        following it) raise ``exc``.  ``exc`` may be an exception instance
        (re-raised each time) or a zero-arg factory."""
        if times < 1:
            raise ValueError("times must be >= 1")
        if after_calls < 0:
            raise ValueError("after_calls must be >= 0")
        with self._lock:
            self._sites.setdefault(site, []).append(
                _ArmedFault(exc=exc, after_calls=after_calls, times=times)
            )

    def reset(self) -> None:
        """Disarm everything (test teardown)."""
        with self._lock:
            self._sites = {}

    def active(self) -> bool:
        """True if any fault is armed (the tier-1 inertness guard)."""
        return bool(self._sites)

    def fired(self, site: str) -> int:
        """How many times ``site``'s armed faults have raised so far."""
        with self._lock:
            return sum(f.raised for f in self._sites.get(site, ()))

    # --- production side ----------------------------------------------------

    def fire(self, site: str) -> None:
        """Called by production seams.  Inert (one falsy check) unless a
        test armed a fault for ``site``."""
        if not self._sites:
            return
        with self._lock:
            faults = self._sites.get(site)
            if not faults:
                return
            for f in faults:
                f.seen += 1
                if f.should_raise():
                    f.raised += 1
                    exc = f.make_exc()
                    break
            else:
                return
        raise exc


#: The process-global injector every guarded seam fires into.
FAULTS = FaultInjector()

#: Exception types :func:`arm_from_env` may construct — an allowlist, not
#: ``eval``: the env var names one of these, never arbitrary code.
_ENV_EXC_TYPES = {
    "OSError": OSError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
    "MemoryError": MemoryError,
}


def arm_from_env(
    env: Optional[Dict[str, str]] = None,
    process_id: Optional[int] = None,
    injector: Optional[FaultInjector] = None,
) -> int:
    """Arm :data:`FAULTS` from a ``TEXTBLAST_FAULTS`` environment spec.

    Spec grammar (``;``-separated entries)::

        site[:after=N][:times=M][:exc=Name]

    e.g. ``TEXTBLAST_FAULTS="multihost.round:after=1:times=2"`` arms an
    ``OSError`` (the default — classified retryable) on the second and third
    fires of the lockstep-round seam.  ``exc`` must name a type in the
    allowlist (OSError, TimeoutError, RuntimeError, MemoryError).

    When ``TEXTBLAST_FAULTS_PROCESS`` is set and ``process_id`` is given,
    arming is skipped unless they match — how a multi-host chaos test faults
    exactly one rank of a real N-process run.  Returns the number of faults
    armed (0 when the spec is absent or gated off).
    """
    import os

    env = os.environ if env is None else env
    injector = FAULTS if injector is None else injector
    spec = env.get("TEXTBLAST_FAULTS", "").strip()
    if not spec:
        return 0
    only = env.get("TEXTBLAST_FAULTS_PROCESS", "").strip()
    if only and process_id is not None and int(only) != int(process_id):
        return 0
    armed = 0
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        site, after_calls, times, exc_name = parts[0], 0, 1, "OSError"
        for p in parts[1:]:
            key, _, val = p.partition("=")
            if key == "after":
                after_calls = int(val)
            elif key == "times":
                times = int(val)
            elif key == "exc":
                exc_name = val
            else:
                raise ValueError(
                    f"unknown TEXTBLAST_FAULTS option {key!r} in {entry!r}"
                )
        try:
            exc_type = _ENV_EXC_TYPES[exc_name]
        except KeyError:
            raise ValueError(
                f"TEXTBLAST_FAULTS exc must be one of "
                f"{sorted(_ENV_EXC_TYPES)}, got {exc_name!r}"
            ) from None
        injector.inject(
            site,
            lambda site=site, exc_type=exc_type: exc_type(
                f"injected fault at {site} (TEXTBLAST_FAULTS)"
            ),
            after_calls=after_calls,
            times=times,
        )
        armed += 1
    return armed
