"""Negotiated resilience for the lockstep multi-host SPMD path.

The single-host degradation ladder (ops/pipeline.py ``_execute_packed``)
makes *unilateral* decisions: retry this batch, split it, rerun it on the
host oracle.  Under ``jax.distributed`` that is exactly what the lockstep
contract forbids — every process must dispatch the same programs in the
same order, so one host quietly re-dispatching (or skipping) a round while
its peers move on desynchronizes the global program sequence and hangs the
job until the coordination-service heartbeat tears it down (~90 s).

This module makes the ladder's decisions *jointly*.  After every lockstep
round each host contributes a 1-element fault flag to a small allgather
(the same ``host_allgather`` machinery the round schedule is negotiated
with, see ``parallel/multihost.py _negotiate_max``) and every host applies
the identical verdict:

* **any host faulted → negotiated retry**: ALL hosts re-dispatch the same
  round — including hosts whose own attempt succeeded, because the compiled
  program is a global SPMD execution that every process must participate
  in.  The shared :class:`RetryPolicy` schedule runs with **jitter forced
  to zero** so every host computes the same backoff for the same attempt
  and the dispatch sequences stay aligned in time as well as in order.
* **retry budget exhausted → negotiated degradation**: every host routes
  its chunk of the round to the bit-exact host oracle.  The degraded round
  is skipped *jointly* — agreement, not dispatch, is what lockstep
  requires, and this is the safe form of the "pad round": a host whose
  device cannot launch the pad program would strand its peers' in-flight
  collectives, whereas a negotiated skip keeps the global program sequence
  identical on every host by construction.
* **persistent faults → negotiated breaker latch**: a per-bucket
  :class:`CircuitBreaker` counts negotiated round failures.  Its state is
  driven *only* by the shared verdict sequence (cooldown is pinned to 0 —
  a wall-clock cooldown would let host clocks disagree about the state),
  so when a bucket trips, every host latches it at the same round and
  routes the rest of that bucket's documents to the host oracle without
  dispatching.

Residual risk, documented rather than hidden: if a compiled program carries
cross-host collectives (XLA's choice) and one host's *launch* fails while a
peer's succeeds, the peer's fetch can block on a collective that never
completes — the verdict negotiation only runs after the fetch returns or
raises.  The data-parallel filter programs this build compiles are
collective-free (see parallel/mesh.py), so the fetch completes locally and
the negotiation always convenes; on topologies where XLA inserts
collectives the heartbeat teardown remains the backstop, exactly as for
hard process death.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..utils.events import EVENTS
from ..utils.metrics import METRICS
from ..utils.trace import TRACER
from .breaker import CircuitBreaker
from .retry import RetryPolicy, classify_error
from .watchdog import WATCHDOG

logger = logging.getLogger(__name__)

__all__ = ["NegotiatedGuard"]


class NegotiatedGuard:
    """Joint fault/verdict protocol for one multi-host run.

    One instance guards one ``run_local_shard`` call (all phases), so the
    per-bucket breaker state persists for the shard's life.  Every
    participating process must construct it with the same config and bucket
    set and drive it through the identical round sequence — the verdict
    allgathers are collectives.
    """

    def __init__(
        self,
        rc=None,
        buckets: Sequence[int] = (),
        sleep: Optional[Callable[[float], None]] = None,
    ) -> None:
        if rc is None:
            from ..config.pipeline import ResilienceConfig

            rc = ResilienceConfig()
        # Jitter MUST be zero: each host computes its own backoff locally,
        # and the negotiated retry only preserves lockstep if every host
        # sleeps the same schedule before re-dispatching.
        overrides = {"jitter": 0.0}
        if sleep is not None:
            overrides["sleep"] = sleep
        self.policy = RetryPolicy.from_config(rc, **overrides)
        # cooldown_s=0 latches the breaker open: its transitions then depend
        # only on the (allgathered, therefore identical) verdict sequence,
        # never on a host-local clock.
        self.breakers: Dict[int, CircuitBreaker] = {
            b: CircuitBreaker(
                rc.breaker_threshold, name=f"negotiated-bucket-{b}",
                cooldown_s=0.0,
            )
            for b in buckets
        }

    # --- verdict exchange ---------------------------------------------------

    def _negotiate(self, local_fault: bool) -> bool:
        """Allgather every host's fault flag; True if ANY host faulted.

        Piggybacks on the same :func:`~textblaster_tpu.parallel.multihost.
        host_allgather` transport the round schedule is negotiated with —
        one int per host per call (XLA allgather on accelerators, the
        coordination-service KV store on multi-process CPU)."""
        return self.negotiate_batch([local_fault])[0]

    def negotiate_batch(self, local_faults: Sequence[bool]) -> list:
        """ONE verdict post carrying the fault flag of EVERY round the
        caller resolved since the last exchange; returns the per-round
        joint verdicts in the same order.

        The window drain in ``run_local_shard`` resolves its in-flight
        rounds in a burst; posting their flags as one vector collapses
        ``len(local_faults)`` transport posts into a single one.  A
        1-element batch posts the identical ``[0|1]`` vector the classic
        per-round :meth:`_negotiate` posted, so depth-1 traffic is
        byte-identical on the wire.  Callers must walk the verdicts in
        order and treat the FIRST fault as authoritative: the flags of the
        rounds behind it were measured on launched-ahead state the joint
        drain is about to discard, so every host voids them identically
        and re-negotiates those rounds at their own (post-drain) resolve."""
        from ..parallel.multihost import host_allgather

        flags = host_allgather(
            np.array([1 if f else 0 for f in local_faults])
        )
        if len(local_faults) > 1:
            METRICS.inc(
                "resilience_negotiated_batched_verdicts_total",
                len(local_faults),
            )
        return [bool(v) for v in (flags.max(axis=0) > 0)]

    def negotiate_freight(
        self, local_faults: Sequence[bool], freight: Sequence[int]
    ):
        """:meth:`negotiate_batch` with extra lanes riding the same post.

        The speculative phase barrier (``run_local_shard``) piggybacks its
        cross-barrier state — join-admission lanes and the next phase's
        optimistic round counts — onto the tail rounds' verdict vector, so
        one allgather replaces what used to be up to three separate posts
        (the win is largest on the file-lease transport, where each post
        is a filesystem round-trip).  Returns ``(verdicts, rows)``: the
        per-round joint verdicts in order, plus every host's raw freight
        lanes as an ``[n_proc, len(freight)]`` int array for the caller to
        reduce (union for join lanes, colmax for round counts).

        Void protocol, the cross-barrier extension of the batched-verdict
        contract: if ANY verdict in ``verdicts`` is a fault, the freight is
        VOID on every host — the counts were measured against tail state
        the joint drain is about to discard, and acting on them would let
        hosts disagree about the next phase's schedule.  Callers void
        speculated launches and the freight together, re-run the faulted
        round under :meth:`run_round` (``prior_fault``), and re-post a
        fresh barrier exchange — every host takes the identical branch
        because the verdicts themselves are allgathered."""
        from ..parallel.multihost import host_allgather

        n = len(local_faults)
        vec = [1 if f else 0 for f in local_faults] + [
            int(x) for x in freight
        ]
        rows = host_allgather(np.array(vec, dtype=np.int64))
        if n > 1:
            METRICS.inc(
                "resilience_negotiated_batched_verdicts_total", n
            )
        verdicts = (
            [bool(v) for v in (rows[:, :n].max(axis=0) > 0)] if n else []
        )
        return verdicts, rows[:, n:]

    @staticmethod
    def _epoch() -> int:
        """Current membership epoch, for labeling verdict trace instants —
        an epoch-aware Perfetto timeline shows which gang composition a
        retry/degradation happened under (lazy import, same cycle-avoidance
        as :meth:`_negotiate`)."""
        from ..parallel.multihost import current_exchange_epoch

        return current_exchange_epoch()

    # --- breaker ------------------------------------------------------------

    def bucket_degraded(self, bucket: int) -> bool:
        """True once ``bucket`` latched open — every host answers the same,
        because the breaker only moves on negotiated verdicts."""
        b = self.breakers.get(bucket)
        return b is not None and b.tripped

    def record_round_success(self, bucket: int) -> None:
        """Book a round whose joint verdict arrived via
        :meth:`negotiate_batch` as a success — the same metrics/breaker
        transition the clean-verdict exit of :meth:`run_round` performs,
        so the breaker's verdict sequence is identical whether a round's
        flag traveled alone or piggybacked in a batch."""
        METRICS.inc("resilience_negotiated_rounds_total")
        self.breakers[bucket].record_success()

    # --- the guarded round --------------------------------------------------

    def run_round(
        self,
        bucket: int,
        dispatch: Callable[[], object],
        fetch: Callable[[object], Dict[str, np.ndarray]],
        inflight: Optional[object] = None,
        launch_fault: bool = False,
        on_fault: Optional[Callable[[], None]] = None,
        prior_fault: bool = False,
        prior_local_fault: bool = False,
    ):
        """Resolve one lockstep round under the negotiated protocol.

        ``dispatch`` launches the round's global program (async) and
        ``fetch`` blocks for this process's host-side stats.  ``inflight``
        carries an already-dispatched result tree (the in-flight window in
        ``run_local_shard``); ``launch_fault`` marks that the overlapped
        launch already raised a retryable error, so the first attempt goes
        straight to the verdict.

        ``on_fault`` runs exactly once, on the FIRST joint fault verdict of
        this round (before the retry/degradation branch) — the window-drain
        hook: launched-ahead younger rounds must be discarded so every
        host's global program order after the verdict is the same
        ``[retry(r), r+1, r+2, ...]`` sequence.  The verdict is allgathered,
        so every host invokes its hook at the identical point.

        ``prior_fault`` marks that this round's FIRST joint verdict was
        already exchanged (fault) via :meth:`negotiate_batch` — the loop
        enters the fault branch directly instead of re-posting it, with
        ``prior_local_fault`` preserving this host's own flag for the
        verdict trace.  Every later attempt negotiates per-round as usual.

        Returns the fetched stats, or ``None`` when all hosts jointly
        degraded the round to the host oracle.  Fatal (deterministic)
        errors propagate immediately — they would repeat identically on
        every retry and on every host.

        Gang reformation (``--survive-peer-loss`` on the file-lease
        transport): the verdict exchange itself can discover a dead peer,
        in which case the transport reforms the gang and raises
        :exc:`GangReformed` *through* this method — deliberately uncaught
        here, because a round verdict cannot be salvaged when the member
        set changed mid-exchange.  The phase driver in ``run_local_shard``
        catches it at the round boundary and replays every unresolved
        round (this one included) over the survivor set; a trace instant
        marks the interruption point.
        """
        from ..errors import GangReformed

        METRICS.inc("resilience_negotiated_rounds_total")
        attempt = 0
        pre_verdict = bool(prior_fault)
        while True:
            if pre_verdict:
                # The batched window exchange already posted this round's
                # first flag and delivered a joint fault — fall through to
                # the fault branch without a second post for the same
                # verdict.
                pre_verdict = False
                local_fault, stats = bool(prior_local_fault), None
                inflight, launch_fault = None, False
                any_fault = True
            else:
                local_fault = bool(launch_fault)
                stats = None
                if not local_fault:
                    try:
                        out = inflight if inflight is not None else dispatch()
                        stats = fetch(out)
                    except BaseException as e:  # noqa: BLE001 — classifier decides
                        if classify_error(e) != "retryable":
                            raise
                        WATCHDOG.escalated(e)
                        logger.warning(
                            "Lockstep round (bucket %s) faulted locally on "
                            "attempt %d: %s",
                            bucket, attempt + 1, e,
                        )
                        local_fault = True
                # Past the first attempt nothing is in flight: a negotiated
                # retry must re-dispatch on EVERY host, succeeded ones
                # included.
                inflight, launch_fault = None, False
                try:
                    any_fault = self._negotiate(local_fault)
                except GangReformed:
                    TRACER.instant(
                        "negotiated_reformed",
                        {"bucket": bucket, "attempt": attempt,
                         "epoch": self._epoch()},
                    )
                    if EVENTS.enabled:
                        EVENTS.emit("negotiated_reformed", bucket=bucket,
                                    attempt=attempt)
                    raise
            if not any_fault:
                self.breakers[bucket].record_success()
                return stats
            TRACER.instant(
                "negotiated_verdict",
                {"bucket": bucket, "local_fault": local_fault,
                 "attempt": attempt, "epoch": self._epoch()},
            )
            if EVENTS.enabled:
                EVENTS.emit("negotiated_verdict", bucket=bucket,
                            local_fault=bool(local_fault), attempt=attempt)
            if on_fault is not None:
                on_fault()
                on_fault = None
            if attempt >= self.policy.max_retries:
                METRICS.inc("resilience_negotiated_degraded_rounds_total")
                TRACER.instant(
                    "negotiated_degraded",
                    {"bucket": bucket, "epoch": self._epoch()},
                )
                if EVENTS.enabled:
                    EVENTS.emit("negotiated_degraded", bucket=bucket)
                self.breakers[bucket].record_failure(
                    "negotiated round retries exhausted"
                )
                logger.error(
                    "Lockstep round (bucket %s) exhausted %d negotiated "
                    "retries; all hosts degrade this round to the host "
                    "oracle.",
                    bucket, self.policy.max_retries,
                )
                return None
            delay = self.policy.delay_for(attempt)
            attempt += 1
            METRICS.inc("resilience_negotiated_retries_total")
            TRACER.instant(
                "negotiated_retry",
                {"bucket": bucket, "attempt": attempt, "backoff_s": delay,
                 "epoch": self._epoch()},
            )
            if EVENTS.enabled:
                EVENTS.emit("negotiated_retry", bucket=bucket,
                            attempt=attempt)
            logger.warning(
                "Negotiated retry %d/%d of lockstep round (bucket %s) on "
                "all hosts, shared backoff %.3fs.",
                attempt, self.policy.max_retries, bucket, delay,
            )
            if delay > 0.0:
                self.policy.sleep(delay)
