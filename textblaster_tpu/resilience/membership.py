"""Elastic gang membership: leased liveness, epochs, and stripe ownership.

PR 4 made multi-host rounds fault-tolerant *within* a fixed gang; this
module makes the gang itself a first-class, mutable object.  Two backends,
matched to what the transports can actually survive (measured on this
container's jax 0.4.x):

* **KV leases** (:class:`KVLeaseStore`) ride the ``jax.distributed``
  coordination-service key-value store — the same transport
  ``host_allgather`` uses on multi-process CPU.  Each process renews
  ``textblast/lease/{rank}`` every ``ttl/3``; when a lockstep exchange's
  deadline expires, the survivor reads the lease table and classifies the
  ranks that never posted as *dead* (lease older than the TTL) or *slow*
  (lease fresh — alive but late), then raises a typed
  :class:`~textblaster_tpu.errors.PeerFailure` naming them.  This backend
  diagnoses failures but cannot outlive them: the coordination service
  force-terminates every healthy task ~90-100 s after a peer stops
  heartbeating (client-side fatal error polling), so exchange deadlines
  must sit well under that window to be useful.

* **File leases** (:class:`FileMembershipStore`) live in a run directory
  on the shared filesystem the shard merge already assumes.  They carry
  the ``--elastic`` mode, where processes are *not* coupled through the
  coordination service at all: each rank owns an input stripe with a
  checkpointed cursor, renews a lease file, and survivors deterministically
  adopt orphaned stripes (lowest live rank) when a lease expires.  A
  relaunched process re-registers a lease under a fresh incarnation and
  reclaims its stripe at the owner's next chunk boundary — restart-in-place
  with zero completed chunks replayed.

Epoch semantics (:class:`EpochTracker`): the membership epoch starts at 1
and bumps whenever the observed live set changes — an eviction (lease
expired) and a rejoin (new lease appears) each bump it.  Epochs namespace
the KV exchange keys (``parallel/multihost.py``), label trace instants and
metrics, and define the boundaries at which elastic ownership may move.

Fencing is lease-based (GFS/Chubby style), not compare-and-swap: an owner
self-fences before every chunk commit (own lease must still be fresh and
the cursor must still name it), so the race window between an adopter's
claim and a zombie owner's last commit is milliseconds against a TTL of
seconds.  Clock skew between hosts must be small relative to the TTL —
the same assumption every lease system on a shared filesystem makes.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..errors import PeerFailure, PipelineError, ReformationFailed
from ..utils.events import EVENTS
from ..utils.metrics import METRICS
from ..utils.trace import TRACER
from .faults import FAULTS

logger = logging.getLogger(__name__)

__all__ = [
    "PeerFailure",
    "MembershipConfig",
    "KVLeaseStore",
    "FileMembershipStore",
    "LeaseHeartbeat",
    "EpochTracker",
    "stripe_owner",
    "assign_stripes",
    "elect_members",
    "LEASE_PREFIX",
]

#: KV-store namespace for per-rank liveness leases.
LEASE_PREFIX = "textblast/lease/"

DEFAULT_LEASE_TTL_S = 10.0
DEFAULT_EXCHANGE_DEADLINE_S = 300.0


@dataclass
class MembershipConfig:
    """Knobs for the membership layer (CLI: ``--elastic``,
    ``--lease-ttl-s``, ``--exchange-deadline-s``)."""

    elastic: bool = False
    lease_ttl_s: float = DEFAULT_LEASE_TTL_S
    exchange_deadline_s: float = DEFAULT_EXCHANGE_DEADLINE_S

    def heartbeat_interval_s(self) -> float:
        """Renewal cadence: 3 renewals per TTL, floored for tiny test TTLs."""
        return max(0.05, self.lease_ttl_s / 3.0)

    def validate(self) -> None:
        if self.lease_ttl_s <= 0:
            raise PipelineError(
                f"--lease-ttl-s must be positive, got {self.lease_ttl_s}"
            )
        if self.exchange_deadline_s <= 0:
            raise PipelineError(
                "--exchange-deadline-s must be positive, got "
                f"{self.exchange_deadline_s}"
            )


def _kv_set(client, key: str, value: str) -> None:
    """``key_value_set`` with overwrite (leases are renewed in place; a
    restarted process must be able to re-post).  Older jaxlib clients
    lack the keyword — fall back to the create-only form."""
    try:
        client.key_value_set(key, value, allow_overwrite=True)
    except TypeError:  # pragma: no cover - jaxlib version dependent
        client.key_value_set(key, value)


class KVLeaseStore:
    """Per-rank liveness leases in the ``jax.distributed`` KV store.

    The value is the renewing host's wall-clock seconds
    (``f"{time.time():.3f}"``); freshness is judged against the reader's
    wall clock, so host clocks must agree to well within the TTL (they
    share NTP on any real deployment; the 2-process tests share a box).
    """

    def __init__(self, client, rank: int, ttl_s: float) -> None:
        self.client = client
        self.rank = int(rank)
        self.ttl_s = float(ttl_s)

    def post(self) -> None:
        """Renew this rank's lease (the heartbeat body)."""
        FAULTS.fire("multihost.lease")
        t0 = time.perf_counter()
        _kv_set(self.client, f"{LEASE_PREFIX}{self.rank}", f"{time.time():.3f}")
        METRICS.observe_hdr(
            "multihost_lease_renew_latency_seconds",
            int((time.perf_counter() - t0) * 1e6),
        )
        METRICS.inc("multihost_lease_renewals_total")

    def read_all(self) -> Dict[int, float]:
        """All ranks' lease timestamps, ``{rank: wall_seconds}``."""
        try:
            entries = self.client.key_value_dir_get(LEASE_PREFIX)
        except Exception as e:  # pragma: no cover - service-state dependent
            logger.warning("lease table read failed: %s", e)
            return {}
        leases: Dict[int, float] = {}
        for item in entries or ():
            # jaxlib returns (key, value) pairs; be liberal about shape.
            try:
                key, value = item[0], item[1]
                leases[int(str(key).rsplit("/", 1)[-1])] = float(value)
            except (ValueError, IndexError, TypeError):
                continue
        return leases

    def resolve_liveness(
        self, ranks: Sequence[int], now: Optional[float] = None
    ) -> Tuple[List[int], List[int]]:
        """Classify ``ranks`` into ``(dead, slow)`` against the lease table.

        A rank with no lease at all is dead (it never registered, or its
        keys were cleaned); a rank whose lease is older than the TTL is
        dead; a rank with a fresh lease is slow — alive but late."""
        now = time.time() if now is None else now
        leases = self.read_all()
        dead, slow = [], []
        for r in ranks:
            ts = leases.get(int(r))
            if ts is None or now - ts > self.ttl_s:
                dead.append(int(r))
            else:
                slow.append(int(r))
        return dead, slow


class FileMembershipStore:
    """Shared-filesystem membership for ``--elastic`` runs.

    Layout under ``root`` (created on register)::

        t0.json            — wall-clock trace origin, written once (O_EXCL)
        lease.rank{r}.json — {"rank", "incarnation", "time", "pid"}
        stripe{s}/         — per-stripe checkpoint dir (cursor + parts)

    Lease writes are atomic (tmp + ``os.replace``) so a reader never sees
    a torn JSON.  Incarnations distinguish a relaunched rank from its dead
    predecessor: lease freshness answers *whether* rank r is live, the
    incarnation answers *which* launch of it.
    """

    def __init__(
        self,
        root: str,
        rank: int,
        ttl_s: float,
        incarnation: Optional[str] = None,
    ) -> None:
        self.root = root
        self.rank = int(rank)
        self.ttl_s = float(ttl_s)
        # Unique per launch: wall-clock ns + pid.  Wall clock is used only
        # for uniqueness, never ordering.
        self.incarnation = incarnation or f"{time.time_ns():x}-{os.getpid()}"

    # --- registration & heartbeat -------------------------------------------

    def register(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        t0 = os.path.join(self.root, "t0.json")
        if not os.path.exists(t0):
            try:
                fd = os.open(t0, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                pass  # a peer won the race — its origin is the run's
            else:
                with os.fdopen(fd, "w", encoding="utf-8") as f:
                    json.dump({"wall_us": int(time.time() * 1e6)}, f)
        self.post()

    def post(self) -> None:
        """Renew this rank's lease file (the heartbeat body)."""
        FAULTS.fire("multihost.lease")
        t0 = time.perf_counter()
        path = self._lease_path(self.rank)
        tmp = f"{path}.tmp.{self.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rank": self.rank,
                    "incarnation": self.incarnation,
                    "time": time.time(),
                    "pid": os.getpid(),
                },
                f,
            )
        os.replace(tmp, path)
        METRICS.observe_hdr(
            "multihost_lease_renew_latency_seconds",
            int((time.perf_counter() - t0) * 1e6),
        )
        METRICS.inc("multihost_lease_renewals_total")

    def withdraw(self) -> None:
        """Remove this rank's lease (clean exit: don't look dead, be gone)."""
        try:
            os.remove(self._lease_path(self.rank))
        except OSError:
            pass

    def _lease_path(self, rank: int) -> str:
        return os.path.join(self.root, f"lease.rank{int(rank)}.json")

    # --- reads ---------------------------------------------------------------

    def read_leases(self) -> Dict[int, dict]:
        leases: Dict[int, dict] = {}
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return leases
        for name in names:
            if not (name.startswith("lease.rank") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name), encoding="utf-8") as f:
                    d = json.load(f)
                leases[int(d["rank"])] = d
            except (OSError, ValueError, KeyError):
                continue  # torn/foreign file: not a live lease
        return leases

    def live_ranks(self, now: Optional[float] = None) -> List[int]:
        """Sorted ranks whose lease is fresher than the TTL."""
        now = time.time() if now is None else now
        return sorted(
            r
            for r, d in self.read_leases().items()
            if now - float(d.get("time", 0.0)) <= self.ttl_s
        )

    def my_lease_fresh(self, now: Optional[float] = None) -> bool:
        """Self-fence predicate: own lease file present, fresh, and still
        this incarnation's (a successor overwriting it means a newer launch
        of this rank took over)."""
        now = time.time() if now is None else now
        d = self.read_leases().get(self.rank)
        if d is not None and d.get("incarnation") == self.incarnation:
            # Heartbeat-starvation gauge: how close the last renewal sits
            # to the TTL at this check (>= 1.0 means the lease went stale
            # — e.g. a GIL-holding compile starved the heartbeat thread).
            METRICS.set(
                "multihost_lease_age_ratio",
                max(0.0, now - float(d.get("time", 0.0))) / self.ttl_s,
            )
        return (
            d is not None
            and d.get("incarnation") == self.incarnation
            and now - float(d.get("time", 0.0)) <= self.ttl_s
        )

    def t0_us(self) -> Optional[int]:
        try:
            with open(os.path.join(self.root, "t0.json"), encoding="utf-8") as f:
                return int(json.load(f)["wall_us"])
        except (OSError, ValueError, KeyError):
            return None

    def stripe_dir(self, stripe: int) -> str:
        path = os.path.join(self.root, f"stripe{int(stripe)}")
        os.makedirs(path, exist_ok=True)
        return path

    def resolve_liveness(
        self, ranks: Sequence[int], now: Optional[float] = None
    ) -> Tuple[List[int], List[int]]:
        """Classify ``ranks`` into ``(dead, slow)`` against the lease files
        (same contract as :meth:`KVLeaseStore.resolve_liveness`, so the
        deadline path's failure report works on either backend)."""
        now = time.time() if now is None else now
        leases = self.read_leases()
        dead, slow = [], []
        for r in ranks:
            d = leases.get(int(r))
            if d is None or now - float(d.get("time", 0.0)) > self.ttl_s:
                dead.append(int(r))
            else:
                slow.append(int(r))
        return dead, slow

    # --- exchange slots (FileLeaseTransport storage) -------------------------
    #
    # One file per (exchange epoch, sequence number, rank) under
    # ``exchange/e{E}/s{S}/rank{r}.json`` — the file-backed twin of the KV
    # transport's ``textblast/allgather/e{E}/s{S}/{r}`` keys.  Posts are
    # atomic (tmp + ``os.replace``) and name the poster's incarnation so a
    # fenced zombie's late post can be ignored by readers.

    def exchange_slot_dir(self, epoch: int, seq: int) -> str:
        return os.path.join(
            self.root, "exchange", f"e{int(epoch)}", f"s{int(seq)}"
        )

    def post_exchange_slot(self, epoch: int, seq: int, payload: str) -> None:
        FAULTS.fire("multihost.exchange.post")
        d = self.exchange_slot_dir(epoch, seq)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"rank{self.rank}.json")
        tmp = f"{path}.tmp.{self.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rank": self.rank,
                    "incarnation": self.incarnation,
                    "data": payload,
                },
                f,
            )
        os.replace(tmp, path)
        METRICS.inc("multihost_file_exchange_posts_total")

    def read_exchange_slot(
        self, epoch: int, seq: int, rank: int
    ) -> Optional[dict]:
        path = os.path.join(
            self.exchange_slot_dir(epoch, seq), f"rank{int(rank)}.json"
        )
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def delete_exchange_slot(self, epoch: int, seq: int) -> None:
        """Drop this rank's slot at ``(epoch, seq)`` and opportunistically
        remove the emptied seq/epoch dirs (the last deleter wins the
        ``rmdir``; everyone else's fails harmlessly on non-empty)."""
        d = self.exchange_slot_dir(epoch, seq)
        try:
            os.remove(os.path.join(d, f"rank{self.rank}.json"))
        except OSError:
            return
        for p in (d, os.path.dirname(d)):
            try:
                os.rmdir(p)
            except OSError:
                break

    # --- incarnation fencing -------------------------------------------------
    #
    # ``fence/rank{r}.{incarnation}.json`` marks one launch of rank ``r``
    # as excluded from the gang.  Fence files are write-once (O_EXCL) and
    # only ever added, so concurrent fencers converge without
    # read-modify-write races; a fenced process discovers its own fence at
    # its next exchange and terminates typed instead of splitting the brain.

    def _fence_dir(self) -> str:
        return os.path.join(self.root, "fence")

    def fence_rank(self, rank: int) -> Tuple[str, bool]:
        """Fence ``rank``'s current lease incarnation (``"any"`` when no
        lease is readable — safe on the coordinated path, which never
        relaunches ranks).  Returns ``(incarnation, newly_fenced)``."""
        d = self.read_leases().get(int(rank))
        inc = str(d["incarnation"]) if d and d.get("incarnation") else "any"
        fdir = self._fence_dir()
        os.makedirs(fdir, exist_ok=True)
        path = os.path.join(fdir, f"rank{int(rank)}.{inc}.json")
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return inc, False
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rank": int(rank),
                    "incarnation": inc,
                    "by": self.rank,
                    "time": time.time(),
                },
                f,
            )
        METRICS.inc("multihost_fenced_ranks_total")
        TRACER.instant(
            "rank_fenced",
            {"rank": int(rank), "incarnation": inc, "by": self.rank},
        )
        if EVENTS.enabled:
            EVENTS.emit("rank_fenced", rank=int(rank), incarnation=inc,
                        by=self.rank)
        return inc, True

    def fenced_ranks(self) -> List[int]:
        """Sorted ranks with at least one fence file (any incarnation)."""
        out = set()
        try:
            names = os.listdir(self._fence_dir())
        except FileNotFoundError:
            return []
        for name in names:
            if not (name.startswith("rank") and name.endswith(".json")):
                continue
            try:
                out.add(int(name[4:].split(".", 1)[0]))
            except ValueError:
                continue
        return sorted(out)

    def is_fenced(self, rank: int, incarnation: str) -> bool:
        fdir = self._fence_dir()
        return os.path.exists(
            os.path.join(fdir, f"rank{int(rank)}.{incarnation}.json")
        ) or os.path.exists(os.path.join(fdir, f"rank{int(rank)}.any.json"))

    def self_fenced(self) -> bool:
        return self.is_fenced(self.rank, self.incarnation)

    # --- reformation proposals ----------------------------------------------

    def _proposal_dir(self, tag: str) -> str:
        return os.path.join(self.root, "reform", tag)

    def post_proposal(self, tag: str, members: Sequence[int]) -> None:
        d = self._proposal_dir(tag)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"rank{self.rank}.json")
        tmp = f"{path}.tmp.{self.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rank": self.rank,
                    "incarnation": self.incarnation,
                    "members": sorted(int(r) for r in members),
                },
                f,
            )
        os.replace(tmp, path)

    def read_proposal(self, tag: str, rank: int) -> Optional[dict]:
        path = os.path.join(self._proposal_dir(tag), f"rank{int(rank)}.json")
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def peer_proposals(self, prefix: str) -> Dict[str, List[int]]:
        """``{attempt_tag: members}`` for every posted proposal whose tag
        starts with ``prefix``, excluding this rank's own posts — the
        joiner's passive view of an in-flight admission election, echoed
        back so every candidate proposes.  One peer proposal per tag
        (lowest-rank poster wins the read, deterministically)."""
        out: Dict[str, List[int]] = {}
        base = os.path.join(self.root, "reform")
        try:
            tags = os.listdir(base)
        except FileNotFoundError:
            return out
        for tag in tags:
            if not tag.startswith(prefix):
                continue
            try:
                names = sorted(os.listdir(os.path.join(base, tag)))
            except OSError:
                continue
            for name in names:
                if not (name.startswith("rank") and name.endswith(".json")):
                    continue
                if name == f"rank{self.rank}.json":
                    continue
                try:
                    with open(
                        os.path.join(base, tag, name), encoding="utf-8"
                    ) as f:
                        p = json.load(f)
                    out[tag] = [int(r) for r in p["members"]]
                except (OSError, ValueError, KeyError, TypeError):
                    continue
                break
        return out

    # --- join requests (live scale-out admission) ----------------------------
    #
    # ``join/rank{r}.json`` is an incarnation-stamped request to enter the
    # gang, posted next to the liveness leases.  A request is only *valid*
    # while its poster also holds a fresh lease of the same incarnation and
    # is unfenced — so a joiner that dies mid-admission (or gets fenced for
    # never proposing) simply stops being a candidate; no cleanup protocol
    # is needed for the gang to proceed un-grown.

    def _join_dir(self) -> str:
        return os.path.join(self.root, "join")

    def post_join_request(self) -> None:
        """Request admission into the running gang (fires the
        ``multihost.join.post`` fault site)."""
        FAULTS.fire("multihost.join.post")
        d = self._join_dir()
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"rank{self.rank}.json")
        tmp = f"{path}.tmp.{self.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "rank": self.rank,
                    "incarnation": self.incarnation,
                    "time": time.time(),
                    "pid": os.getpid(),
                },
                f,
            )
        os.replace(tmp, path)
        METRICS.inc("multihost_join_requests_total")
        TRACER.instant(
            "join_request",
            {"rank": self.rank, "incarnation": self.incarnation},
        )
        if EVENTS.enabled:
            EVENTS.emit("join_request", rank=self.rank,
                        incarnation=self.incarnation)

    def read_join_requests(
        self, now: Optional[float] = None
    ) -> Dict[int, dict]:
        """Valid join requests: ``{rank: request}`` where the poster is
        unfenced and its lease (same incarnation) is fresh."""
        now = time.time() if now is None else now
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self._join_dir())
        except FileNotFoundError:
            return out
        leases = self.read_leases()
        for name in names:
            if not (name.startswith("rank") and name.endswith(".json")):
                continue
            try:
                with open(
                    os.path.join(self._join_dir(), name), encoding="utf-8"
                ) as f:
                    d = json.load(f)
                rank, inc = int(d["rank"]), str(d["incarnation"])
            except (OSError, ValueError, KeyError, TypeError):
                continue
            if self.is_fenced(rank, inc):
                continue
            lease = leases.get(rank)
            if (
                lease is None
                or lease.get("incarnation") != inc
                or now - float(lease.get("time", 0.0)) > self.ttl_s
            ):
                continue
            out[rank] = d
        return out

    def clear_join_request(self, rank: int) -> None:
        """Drop ``rank``'s join request (admission completed, or the
        joiner withdrew/was fenced)."""
        try:
            os.remove(
                os.path.join(self._join_dir(), f"rank{int(rank)}.json")
            )
        except OSError:
            pass

    # --- roster (gang-published membership view) -----------------------------
    #
    # ``roster.json`` is the gang's authoritative published membership:
    # written by every member after each admission/reformation election.
    # A joiner polls it to learn (a) the member set it must echo in the
    # admission election and (b) that its admission landed, plus the
    # exchange epoch it must sync to before its first collective.

    def write_roster(
        self,
        members: Sequence[int],
        membership_epoch: int,
        exchange_epoch: int,
    ) -> None:
        path = os.path.join(self.root, "roster.json")
        tmp = f"{path}.tmp.{self.incarnation}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(
                {
                    "members": sorted(int(r) for r in members),
                    "membership_epoch": int(membership_epoch),
                    "exchange_epoch": int(exchange_epoch),
                    "by": self.rank,
                    "time": time.time(),
                },
                f,
            )
        os.replace(tmp, path)

    def read_roster(self) -> Optional[dict]:
        try:
            with open(
                os.path.join(self.root, "roster.json"), encoding="utf-8"
            ) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class LeaseHeartbeat:
    """Daemon thread renewing a lease store every ``interval_s``.

    Renewal failures are tolerated ``max_failures`` times in a row (a
    shared-filesystem blip should not kill the renewer), then the thread
    stops and ``failed`` latches — the owner's next self-fence sees the
    stale lease and stops committing, which is exactly the contract the
    adopters rely on."""

    def __init__(self, store, interval_s: float, max_failures: int = 5) -> None:
        self.store = store
        self.interval_s = float(interval_s)
        self.max_failures = int(max_failures)
        self.failed = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="textblast-lease", daemon=True
        )

    def start(self) -> "LeaseHeartbeat":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        failures = 0
        while not self._stop.wait(self.interval_s):
            try:
                self.store.post()
                failures = 0
            except Exception as e:  # noqa: BLE001 — renewal is best-effort
                failures += 1
                logger.warning(
                    "lease renewal failed (%d/%d): %s",
                    failures, self.max_failures, e,
                )
                if failures >= self.max_failures:
                    self.failed = True
                    logger.error(
                        "lease renewal abandoned after %d consecutive "
                        "failures; this process will self-fence at its next "
                        "commit boundary", failures,
                    )
                    return


def stripe_owner(stripe: int, live: Sequence[int]) -> Optional[int]:
    """Deterministic ownership rule every rank computes identically:
    stripe ``s`` belongs to rank ``s`` while rank ``s`` is live; an
    orphaned stripe is adopted by the **lowest live rank** (the same
    successor rule that fails merge duty over).  ``None`` when nobody is
    live to own it.  :func:`assign_stripes` is the scale-out-aware
    generalization (orphan spreading + joiner rebalancing); this
    single-stripe rule remains for the fixed-gang cases."""
    live = sorted(int(r) for r in live)
    if not live:
        return None
    return int(stripe) if int(stripe) in live else live[0]


def assign_stripes(
    pending: Sequence[int],
    live: Sequence[int],
    num_stripes: int,
) -> Dict[int, Optional[int]]:
    """Deterministic stripe→owner assignment every rank computes
    identically from the shared ``(pending, live)`` view — the scale-out
    generalization of :func:`stripe_owner`:

    1. **Home affinity** — pending stripe ``s`` belongs to rank ``s``
       while rank ``s`` is live.
    2. **Orphans** — a pending stripe whose home rank is dead goes to the
       least-loaded live rank (ties → lowest rank), which degenerates to
       :func:`stripe_owner`'s lowest-live-rank rule whenever a single
       survivor remains.
    3. **Joiner rebalance** — an idle *joiner* (rank ``>= num_stripes``,
       so it has no home stripe ever) steals one pending stripe from the
       most-loaded donor (ties → highest rank; the donor's highest stripe
       moves).  The donor discovers the move at its next committed chunk
       boundary (its fence raises ``StripeLost``) and the joiner adopts
       the remaining cursor — dead-stripe adoption run in reverse, so no
       chunk is processed twice and merge order is unchanged.

    Pure function of its inputs: the assignment is stable until
    ``pending`` or ``live`` changes, so transient disagreement between
    ranks reading the lease table at different instants converges the
    same way stripe adoption always has (fence + atomic cursor rename).
    ``None`` owners mean nobody is live."""
    live_s = sorted({int(r) for r in live})
    pending_s = sorted({int(s) for s in pending})
    if not live_s:
        return {s: None for s in pending_s}
    assign: Dict[int, Optional[int]] = {}
    load = {r: 0 for r in live_s}
    orphans = []
    for s in pending_s:
        if s in load:
            assign[s] = s
            load[s] += 1
        else:
            orphans.append(s)
    for s in orphans:
        r = min(live_s, key=lambda q: (load[q], q))
        assign[s] = r
        load[r] += 1
    stolen: set = set()
    for thief in [
        r for r in live_s if load[r] == 0 and r >= int(num_stripes)
    ]:
        donors = [
            r
            for r in live_s
            if r != thief
            and any(o == r and s not in stolen for s, o in assign.items())
        ]
        if not donors:
            break
        donor = max(donors, key=lambda q: (load[q], q))
        take = max(
            s for s, o in assign.items() if o == donor and s not in stolen
        )
        assign[take] = thief
        stolen.add(take)
        load[donor] -= 1
        load[thief] += 1
    return assign


def elect_members(
    store: FileMembershipStore,
    members: Sequence[int],
    suspects: Sequence[int],
    tag: str,
    deadline_s: float,
    max_attempts: int = 8,
    poll_s: float = 0.02,
    joiners: Sequence[int] = (),
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Deterministic survivor election for gang reformation and admission.

    Every survivor of a failed lockstep exchange is blocked at the *same*
    ``(epoch, seq)`` (exchanges are blocking and lockstep), so ``tag`` —
    derived from those coordinates — names a common rendezvous directory
    with no extra negotiation.  The protocol is fence-then-elect:

    1. Fence every suspect's incarnation.  Fencing makes exclusion safe
       regardless of whether the suspect was dead or merely wedged — a
       fenced zombie discovers the fence at its next exchange post and
       terminates typed rather than splitting the brain.
    2. Compute candidates = ``members`` plus unfenced ``joiners`` minus
       all fenced ranks (the fence table is shared and only ever grows,
       so survivors converge on it).
    3. Post a proposal naming the candidate set; wait (deadline-bounded)
       for a proposal from every candidate.
    4. All proposals identical → elected.  A missing proposer joins the
       suspects for the next attempt; a disagreeing proposal's exclusions
       of *base members* are adopted (union of everyone's suspicions) and
       any joiners it admits that this process hasn't seen are merged in —
       a joiner is never suspected merely for being unknown to a peer
       (only the shared fence table excludes a dead joiner), so a join
       request racing a fence converges to the same member set on every
       survivor regardless of observation order.

    ``joiners`` generalizes reformation into **admission**: ranks outside
    ``members`` with a posted join request become candidates too.  An
    admitted joiner appears in ``new_members``; a joiner that dies
    mid-election is fenced like any silent candidate but never reported
    in ``newly_dead`` (it was not a member yet).

    Returns ``(new_members, newly_dead)``.  Raises
    :class:`~textblaster_tpu.errors.ReformationFailed` when this process
    finds itself fenced or the election exhausts ``max_attempts``.

    Mutual-suspicion caveat: if two partitions each fence the other (e.g.
    a filesystem stall on both sides), *both* find themselves fenced and
    terminate typed.  That sacrifices availability for safety — no member
    set containing a fenced rank is ever elected.
    """
    me = store.rank
    members = sorted({int(r) for r in members})
    joiners = {int(r) for r in joiners} - set(members)
    suspects = {int(r) for r in suspects} - {me}
    for attempt in range(max_attempts):
        FAULTS.fire("multihost.reform")
        for r in sorted(suspects):
            store.fence_rank(r)
        if store.self_fenced():
            raise ReformationFailed(
                f"rank {me} (incarnation {store.incarnation}) was fenced by "
                "a peer during reformation — terminating to avoid "
                "split-brain",
                rank=me,
            )
        fenced = set(store.fenced_ranks()) - {me}
        candidates = sorted(
            r for r in set(members) | joiners if r not in fenced
        )
        if not candidates or me not in candidates:
            raise ReformationFailed(
                f"rank {me} computed an empty/self-excluding candidate set "
                f"{candidates} from members {members}",
                rank=me,
            )
        attempt_tag = f"{tag}.a{attempt}"
        store.post_proposal(attempt_tag, candidates)
        deadline = time.monotonic() + float(deadline_s)
        proposals: Dict[int, dict] = {}
        while True:
            for r in candidates:
                if r not in proposals:
                    p = store.read_proposal(attempt_tag, r)
                    if p is not None:
                        proposals[r] = p
            if len(proposals) == len(candidates):
                break
            if time.monotonic() >= deadline:
                break
            time.sleep(poll_s)
        missing = [r for r in candidates if r not in proposals]
        if not missing and all(
            p.get("members") == candidates for p in proposals.values()
        ):
            newly_dead = tuple(r for r in members if r not in candidates)
            return tuple(candidates), newly_dead
        # A candidate that never proposed is itself suspect now; a
        # disagreeing candidate saw fences (or join requests) this process
        # hasn't — adopt its exclusions of base members, merge in its
        # joiners, and retry against the merged fence table.
        suspects |= set(missing)
        for p in proposals.values():
            pm = {int(r) for r in p.get("members", ())}
            suspects |= set(members) - pm
            joiners |= pm - set(members)
        suspects -= {me}
    raise ReformationFailed(
        f"election did not converge after {max_attempts} attempts "
        f"(members {members}, last suspects {sorted(suspects)})",
        rank=me,
    )


class EpochTracker:
    """Observes live-set changes and turns them into epoch bumps.

    ``observe(live)`` returns a list of human-readable transition strings
    (empty when nothing changed) and maintains the counters/instants:
    ``multihost_membership_epoch`` (gauge), ``multihost_evictions_total``
    and ``multihost_rejoins_total``, plus ``membership_evict`` /
    ``membership_rejoin`` trace instants carrying the epoch.

    A rank appearing that was *never* in any prior live set is a live
    scale-out **join** (not a restart-in-place rejoin): it gets a
    ``membership_join`` instant, and exactly one member — the lowest rank
    of the previous live set — counts ``multihost_rank_joins_total``, so
    the sum-merged run report reads joins, not member-observations.  (The
    joiner's own first ``observe`` baselines with itself included, so it
    never counts its own admission.)"""

    def __init__(self, rank: int) -> None:
        self.rank = int(rank)
        self.epoch = 1
        self.live: Optional[Tuple[int, ...]] = None
        self.ever: set = set()
        METRICS.set("multihost_membership_epoch", self.epoch)

    def observe(self, live: Sequence[int]) -> List[str]:
        now = tuple(sorted(int(r) for r in live))
        if self.live is None:
            self.live = now
            self.ever = set(now)
            return []
        if now == self.live:
            return []
        events: List[str] = []
        evicted = set(self.live) - set(now)
        appeared = set(now) - set(self.live)
        prev_min = min(self.live) if self.live else None
        self.epoch += 1
        METRICS.set("multihost_membership_epoch", self.epoch)
        for r in sorted(evicted):
            METRICS.inc("multihost_evictions_total")
            TRACER.instant(
                "membership_evict", {"rank": r, "epoch": self.epoch}
            )
            if EVENTS.enabled:
                EVENTS.emit("membership_evict", rank=r, epoch=self.epoch)
            events.append(f"evicted rank {r} (lease expired); epoch {self.epoch}")
        for r in sorted(appeared):
            if r in self.ever:
                METRICS.inc("multihost_rejoins_total")
                TRACER.instant(
                    "membership_rejoin", {"rank": r, "epoch": self.epoch}
                )
                if EVENTS.enabled:
                    EVENTS.emit("membership_rejoin", rank=r,
                                epoch=self.epoch)
                events.append(f"rank {r} rejoined; epoch {self.epoch}")
            else:
                if prev_min == self.rank:
                    METRICS.inc("multihost_rank_joins_total")
                TRACER.instant(
                    "membership_join", {"rank": r, "epoch": self.epoch}
                )
                if EVENTS.enabled:
                    EVENTS.emit("membership_join", rank=r, epoch=self.epoch)
                events.append(
                    f"rank {r} joined the gang; epoch {self.epoch}"
                )
        self.ever |= set(now)
        self.live = now
        return events
