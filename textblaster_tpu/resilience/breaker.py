"""Circuit breaker for the device execution path.

A device batch that exhausts its whole degradation ladder (retry -> split ->
host-oracle rerun) still *completes* — the host rung is bit-exact — but each
such batch costs the full host pipeline.  When the device keeps failing
batch after batch (dead TPU slice, wedged remote tunnel), paying ladder
latency per batch is strictly worse than admitting the device is gone:
after ``threshold`` consecutive failures the breaker trips and the run
degrades wholesale to the host backend.  The transition is recorded in
METRICS (``resilience_breaker_trips_total`` counter +
``resilience_breaker_open`` gauge) and logged once.

Half-open recovery: a long shard should not stay host-bound after a
transient outage (tunnel blip, preempted slice that came back).  After
``cooldown_s`` of open time, the next ``allow_request()`` grants exactly one
probe batch (half-open).  If that batch succeeds the breaker closes and the
run returns to the device; if it fails the breaker reopens with a fresh
cooldown.  ``cooldown_s=0`` disables probing — the breaker latches for the
run's life (the pre-half-open behavior).
"""

from __future__ import annotations

import logging
import threading
import time

from ..utils.events import EVENTS
from ..utils.metrics import METRICS
from ..utils.trace import TRACER

logger = logging.getLogger(__name__)

__all__ = ["CircuitBreaker"]

_CLOSED = "closed"
_OPEN = "open"
_HALF_OPEN = "half_open"


class CircuitBreaker:
    """Trip after ``threshold`` *consecutive* failures; a success resets the
    streak.  While open, ``allow_request()`` is False until ``cooldown_s``
    elapses, then grants one half-open probe.  A probe success closes the
    breaker (``record_success`` closes *only* from half-open: a success
    recorded while open belongs to a dispatch that predates the trip and must
    not untrip it); a probe failure reopens with a fresh cooldown."""

    def __init__(
        self,
        threshold: int = 3,
        name: str = "device",
        cooldown_s: float = 0.0,
        clock=time.monotonic,
    ) -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        self.threshold = threshold
        self.name = name
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._state = _CLOSED
        self._opened_at = 0.0

    @property
    def tripped(self) -> bool:
        return self._state != _CLOSED

    @property
    def state(self) -> str:
        return self._state

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    def allow_request(self) -> bool:
        """True if the caller may dispatch to the device now.

        Closed: always.  Open: False until the cooldown elapses, then the
        first caller transitions to half-open and is granted the probe
        (subsequent callers see False until the probe resolves)."""
        with self._lock:
            if self._state == _CLOSED:
                return True
            if self._state == _HALF_OPEN:
                # A probe is already in flight; hold further traffic.
                return False
            if self.cooldown_s <= 0:
                return False
            if self._clock() - self._opened_at < self.cooldown_s:
                return False
            self._state = _HALF_OPEN
        METRICS.inc("resilience_breaker_probe_total")
        TRACER.instant("breaker_probe", {"breaker": self.name})
        if EVENTS.enabled:
            EVENTS.emit("breaker_probe", seam=self.name)
        logger.warning(
            "Circuit breaker '%s' half-open after %.1fs cooldown; probing "
            "the device with one batch.",
            self.name,
            self.cooldown_s,
        )
        return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != _HALF_OPEN:
                return
            self._state = _CLOSED
        METRICS.inc("resilience_breaker_recoveries_total")
        TRACER.instant("breaker_recovery", {"breaker": self.name})
        if EVENTS.enabled:
            EVENTS.emit("breaker_recovery", seam=self.name)
        METRICS.set("resilience_breaker_open", 0)
        logger.warning(
            "Circuit breaker '%s' closed: half-open probe succeeded; "
            "resuming device dispatch.",
            self.name,
        )

    def record_failure(self, cause: str = "") -> None:
        with self._lock:
            if self._state == _OPEN:
                return
            if self._state == _HALF_OPEN:
                # Probe failed: reopen with a fresh cooldown.
                self._state = _OPEN
                self._opened_at = self._clock()
                reopened = True
            else:
                self._consecutive_failures += 1
                if self._consecutive_failures < self.threshold:
                    return
                self._state = _OPEN
                self._opened_at = self._clock()
                reopened = False
        if reopened:
            METRICS.set("resilience_breaker_open", 1)
            TRACER.instant("breaker_reopen", {"breaker": self.name})
            if EVENTS.enabled:
                EVENTS.emit("breaker_reopen", seam=self.name, cause=cause)
            logger.error(
                "Circuit breaker '%s' reopened: half-open probe failed%s; "
                "cooling down for %.1fs.",
                self.name,
                f" (last: {cause})" if cause else "",
                self.cooldown_s,
            )
            return
        METRICS.inc("resilience_breaker_trips_total")
        TRACER.instant("breaker_trip",
                       {"breaker": self.name, "cause": cause})
        if EVENTS.enabled:
            EVENTS.emit("breaker_trip", seam=self.name,
                        failures=self.threshold, cause=cause)
        METRICS.set("resilience_breaker_open", 1)
        logger.error(
            "Circuit breaker '%s' tripped after %d consecutive failures%s; "
            "degrading to the host backend%s.",
            self.name,
            self.threshold,
            f" (last: {cause})" if cause else "",
            (
                f" (will probe after {self.cooldown_s:.1f}s)"
                if self.cooldown_s > 0
                else " for the rest of the run"
            ),
        )
