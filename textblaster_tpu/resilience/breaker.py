"""Circuit breaker for the device execution path.

A device batch that exhausts its whole degradation ladder (retry -> split ->
host-oracle rerun) still *completes* — the host rung is bit-exact — but each
such batch costs the full host pipeline.  When the device keeps failing
batch after batch (dead TPU slice, wedged remote tunnel), paying ladder
latency per batch is strictly worse than admitting the device is gone:
after ``threshold`` consecutive failures the breaker trips and the run
degrades wholesale to the host backend.  The transition is recorded in
METRICS (``resilience_breaker_trips_total`` counter +
``resilience_breaker_open`` gauge) and logged once.
"""

from __future__ import annotations

import logging
import threading

from ..utils.metrics import METRICS

logger = logging.getLogger(__name__)

__all__ = ["CircuitBreaker"]


class CircuitBreaker:
    """Trip after ``threshold`` *consecutive* failures; any success resets
    the streak.  Once open it stays open for the life of the run — the
    failure modes it guards (lost device, dead tunnel) do not heal
    mid-stream, and flapping between backends would make outcome attribution
    meaningless."""

    def __init__(self, threshold: int = 3, name: str = "device") -> None:
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = threshold
        self.name = name
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._tripped = False

    @property
    def tripped(self) -> bool:
        return self._tripped

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0

    def record_failure(self, cause: str = "") -> None:
        with self._lock:
            if self._tripped:
                return
            self._consecutive_failures += 1
            if self._consecutive_failures < self.threshold:
                return
            self._tripped = True
        METRICS.inc("resilience_breaker_trips_total")
        METRICS.set("resilience_breaker_open", 1)
        logger.error(
            "Circuit breaker '%s' tripped after %d consecutive failures%s; "
            "degrading to the host backend for the rest of the run.",
            self.name,
            self.threshold,
            f" (last: {cause})" if cause else "",
        )
