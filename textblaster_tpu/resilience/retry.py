"""Retry policy: exponential backoff + jitter with an error classifier.

The classifier is the load-bearing piece: only *transient* faults — device
runtime errors (preempted slice, dropped tunnel connection, resource
exhaustion), OS-level I/O hiccups — are worth re-attempting.  Deterministic
pipeline errors (a filter decision, a config problem, a checkpoint
fingerprint mismatch) repeat identically on every attempt and must surface
immediately; retrying them only delays the failure and hides its cause.

The clock is injectable (``sleep=``/``rng=``) so tier-1 unit tests cover the
full backoff schedule without ever sleeping for real.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple, TypeVar

from ..errors import (
    CheckpointError,
    ConfigError,
    ConfigValidationError,
    DocumentFiltered,
    PipelineError,
    RetryExhaustedError,
    StallError,
    StepError,
)
from ..utils.events import EVENTS
from ..utils.metrics import METRICS
from ..utils.trace import TRACER
from .watchdog import WATCHDOG

logger = logging.getLogger(__name__)

__all__ = [
    "RetryPolicy",
    "classify_error",
    "is_oom_error",
    "is_retryable_error",
]

T = TypeVar("T")

# Message markers of transient device/transport faults.  XLA runtime errors
# surface as `XlaRuntimeError` (jaxlib; exact class location varies by
# version) carrying a gRPC-style status in the message; the remote-tunnel
# backend adds plain transport phrasing ("connection", "response body
# closed" — the failure that killed the first round-5 TPU bench run).
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "CANCELLED",
    "preempt",
    "connection",
    "socket",
    "timed out",
    "timeout",
    "temporarily",
    "response body closed",
    "out of memory",
)

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM", "oom")

# Errors no outer retry loop should re-attempt: deterministic pipeline
# errors repeat identically, and RetryExhaustedError means a budget was
# already spent on this fault (nested policies must not multiply attempts).
_DETERMINISTIC_TYPES = (
    DocumentFiltered,
    StepError,
    ConfigError,
    ConfigValidationError,
    CheckpointError,
    RetryExhaustedError,
)


def _message_transient(exc: BaseException) -> bool:
    msg = str(exc)
    return any(marker in msg for marker in _TRANSIENT_MARKERS)


def is_oom_error(exc: BaseException) -> bool:
    """Device out-of-memory — the ladder's split-in-half rung targets these.
    Unwraps :class:`RetryExhaustedError` so an OOM that survived the retry
    budget still routes to the split rung."""
    if isinstance(exc, RetryExhaustedError):
        exc = exc.last
    if isinstance(exc, MemoryError):
        return True
    msg = str(exc)
    return any(marker in msg for marker in _OOM_MARKERS)


def is_retryable_error(exc: BaseException) -> bool:
    return classify_error(exc) == "retryable"


def classify_error(exc: BaseException) -> str:
    """``"retryable"`` (transient device/IO fault) or ``"fatal"``
    (deterministic — do not re-attempt)."""
    if isinstance(exc, _DETERMINISTIC_TYPES):
        return "fatal"
    if isinstance(exc, StallError):
        # Watchdog stall: the stalled stage may complete on a re-attempt
        # (re-dispatch, fresh fetch), and the degradation ladder bounds the
        # damage if it never does — explicitly retryable so a hang enters
        # the same recovery machinery as a raised transient fault.
        return "retryable"
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return "fatal"
    if isinstance(exc, (OSError, TimeoutError, ConnectionError, MemoryError)):
        # IOError/socket/timeout family: the transient-by-construction bucket.
        return "retryable"
    if type(exc).__name__ == "XlaRuntimeError":
        # Device runtime fault: transient statuses retry; INVALID_ARGUMENT /
        # compile-shape errors repeat identically.
        return "retryable" if _message_transient(exc) else "fatal"
    if isinstance(exc, PipelineError):
        # Remaining pipeline errors (ParquetError, IoError, Unexpected…):
        # retry only when the message says transient transport/IO.
        return "retryable" if _message_transient(exc) else "fatal"
    return "retryable" if _message_transient(exc) else "fatal"


class RetryPolicy:
    """Exponential backoff + jitter around a callable.

    ``max_retries`` counts re-attempts *after* the first try (``0`` disables
    retrying while keeping classification/metrics).  Delays follow
    ``base * multiplier**k`` capped at ``max_delay``, each widened by up to
    ``jitter`` fraction of itself (seeded ``rng`` for determinism in tests).
    """

    def __init__(
        self,
        max_retries: int = 3,
        base_delay: float = 0.05,
        max_delay: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.5,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
        classify: Callable[[BaseException], str] = classify_error,
    ) -> None:
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.max_retries = max_retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.sleep = sleep
        self.rng = rng if rng is not None else random.Random()
        self.classify = classify

    def delay_for(self, attempt: int) -> float:
        """Backoff for re-attempt ``attempt`` (0-based), jitter applied."""
        d = min(self.base_delay * (self.multiplier ** attempt), self.max_delay)
        if self.jitter > 0.0:
            d *= 1.0 + self.rng.uniform(0.0, self.jitter)
        return d

    def run(
        self,
        fn: Callable[[], T],
        seam: str = "generic",
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
    ) -> T:
        """Call ``fn`` until it succeeds, a fatal error surfaces, or retries
        are exhausted.  Raises the *last* error on exhaustion (chained), so
        genuine failures keep their type and message.

        ``seam`` labels metrics (``resilience_retries_<seam>_total``);
        ``on_retry(exc, attempt)`` observes each re-attempt.  Exhausting the
        budget on a *retryable* error raises
        :class:`~textblaster_tpu.errors.RetryExhaustedError` (a
        ``PipelineError``, so CLI-level handling stays clean) chained to the
        last underlying error; fatal errors re-raise untouched.
        """
        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classifier decides
                if self.classify(e) != "retryable":
                    raise
                WATCHDOG.escalated(e)
                if attempt >= self.max_retries:
                    METRICS.inc("resilience_retry_exhausted_total")
                    if EVENTS.enabled:
                        EVENTS.emit("retry_exhausted", seam=seam,
                                    attempts=attempt + 1,
                                    error=type(e).__name__)
                    raise RetryExhaustedError(seam, attempt + 1, e) from e
                delay = self.delay_for(attempt)
                attempt += 1
                METRICS.inc("resilience_retries_total")
                METRICS.inc(f"resilience_retries_{seam}_total")
                TRACER.instant(
                    "retry", {"seam": seam, "attempt": attempt,
                              "error": type(e).__name__}
                )
                if EVENTS.enabled:
                    EVENTS.emit("retry", seam=seam, attempt=attempt,
                                error=type(e).__name__)
                logger.warning(
                    "Transient fault at seam '%s' (attempt %d/%d, backing off "
                    "%.3fs): %s",
                    seam, attempt, self.max_retries, delay, e,
                )
                if on_retry is not None:
                    on_retry(e, attempt)
                if delay > 0.0:
                    self.sleep(delay)

    @classmethod
    def from_config(cls, rc, **overrides) -> "RetryPolicy":
        """Build from a :class:`~textblaster_tpu.config.pipeline.ResilienceConfig`."""
        kw = dict(
            max_retries=rc.max_retries,
            base_delay=rc.backoff_base_s,
            max_delay=rc.backoff_max_s,
            multiplier=rc.backoff_multiplier,
            jitter=rc.jitter,
        )
        kw.update(overrides)
        return cls(**kw)
