"""Dead-letter sink: the opt-in third Parquet file for failed rows.

The reference drops Error outcomes into *neither* output file, leaving only
a count mismatch (SURVEY.md §7 quirk #2) — and this build's default
preserves that observable behavior.  ``--errors-file errors.parquet`` opts
into a durable trace instead: every Error outcome and every quarantined
unreadable row lands here with enough context (step, reason, worker) to
triage or replay it later, the quarantine discipline production pipelines
treat as first-class.

Schema (all nullable — read errors have no document):

* ``id`` / ``source`` / ``text`` — the document, when one exists;
* ``step``   — pipeline step that failed (``read`` for reader-side rows);
* ``reason`` — the error message;
* ``worker`` — worker id that observed the failure.
"""

from __future__ import annotations

import json
import re
from typing import List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..data_model import ProcessingOutcome
from ..errors import ParquetError, PipelineError
from ..utils.metrics import METRICS

__all__ = [
    "DEADLETTER_SCHEMA",
    "DeadLetterSink",
    "outcome_row",
    "read_error_row",
]

DEADLETTER_SCHEMA = pa.schema(
    [
        pa.field("id", pa.string(), nullable=True),
        pa.field("source", pa.string(), nullable=True),
        pa.field("text", pa.string(), nullable=True),
        pa.field("metadata", pa.string(), nullable=True),
        pa.field("step", pa.string(), nullable=True),
        pa.field("reason", pa.string(), nullable=True),
        pa.field("worker", pa.string(), nullable=True),
    ]
)

# Error outcomes carry the StepError's rendered message
# ("Error in processing step 'X': ..."); recover the step name from it so
# the wire format of ProcessingOutcome stays untouched.
_STEP_RE = re.compile(r"processing step '([^']+)'")

_WRITE_BATCH_SIZE = 500  # producer_logic.rs:21 parity with the main writers


def outcome_row(outcome: ProcessingOutcome) -> dict:
    """Dead-letter row for one Error outcome (worker's swallowed hard error)."""
    doc = outcome.document
    m = _STEP_RE.search(outcome.error_message or "")
    return {
        "id": doc.id,
        "source": doc.source,
        "text": doc.content,
        "metadata": (
            json.dumps(doc.metadata, ensure_ascii=False, separators=(",", ":"))
            if doc.metadata
            else None
        ),
        "step": m.group(1) if m else None,
        "reason": outcome.error_message,
        "worker": outcome.worker_id or None,
    }


def read_error_row(err: PipelineError) -> dict:
    """Dead-letter row for one unreadable/quarantined row (no document)."""
    return {
        "id": None,
        "source": None,
        "text": None,
        "metadata": None,
        "step": "read",
        "reason": str(err),
        "worker": None,
    }


class DeadLetterSink:
    """Buffered Parquet writer for failed rows.

    The file is created eagerly on construction so an error-free run still
    leaves a well-formed (empty) dead-letter file — "no errors" and "sink was
    never wired" must be distinguishable from the artifact alone.
    """

    def __init__(self, path: str, batch_size: int = _WRITE_BATCH_SIZE) -> None:
        import os

        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        try:
            self._writer: Optional[pq.ParquetWriter] = pq.ParquetWriter(
                path, DEADLETTER_SCHEMA
            )
        except Exception as e:
            raise ParquetError(str(e)) from e
        self.path = path
        self.batch_size = batch_size
        self.rows_written = 0
        self._rows: List[dict] = []

    # --- recording ----------------------------------------------------------

    def record(
        self,
        id: Optional[str] = None,
        source: Optional[str] = None,
        text: Optional[str] = None,
        metadata: Optional[str] = None,
        step: Optional[str] = None,
        reason: Optional[str] = None,
        worker: Optional[str] = None,
    ) -> None:
        self.record_row(
            {
                "id": id,
                "source": source,
                "text": text,
                "metadata": metadata,
                "step": step,
                "reason": reason,
                "worker": worker,
            }
        )

    def record_row(self, row: dict) -> None:
        """Append one pre-built row dict (see :func:`outcome_row`)."""
        if self._writer is None:
            raise ParquetError(f"dead-letter sink '{self.path}' is closed")
        self._rows.append({name: row.get(name) for name in DEADLETTER_SCHEMA.names})
        self.rows_written += 1
        METRICS.inc("deadletter_rows_total")
        if len(self._rows) >= self.batch_size:
            self._flush()

    def record_outcome(self, outcome: ProcessingOutcome) -> None:
        """Route one Error outcome (worker_logic.rs's swallowed hard error)."""
        self.record_row(outcome_row(outcome))

    def record_read_error(self, err: PipelineError) -> None:
        """Route one unreadable/quarantined row (no document to attach)."""
        self.record_row(read_error_row(err))

    # --- lifecycle ----------------------------------------------------------

    def _flush(self) -> None:
        if not self._rows:
            return
        if self._writer is None:
            raise ParquetError(f"dead-letter sink '{self.path}' is closed")
        cols = {
            name: pa.array([r[name] for r in self._rows], pa.string())
            for name in DEADLETTER_SCHEMA.names
        }
        try:
            self._writer.write_batch(
                pa.record_batch(
                    [cols[n] for n in DEADLETTER_SCHEMA.names],
                    schema=DEADLETTER_SCHEMA,
                )
            )
        except Exception as e:
            raise ParquetError(str(e)) from e
        self._rows.clear()

    def close(self) -> None:
        if self._writer is not None:
            try:
                self._flush()
            finally:
                self._writer.close()
                self._writer = None

    def __enter__(self) -> "DeadLetterSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
